"""Observability CLI — render a journal as budgets, timelines, causal
traces, and a live dashboard.

    python -m shifu_tensorflow_tpu.obs summary --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs tail    --journal /tmp/job.jsonl -n 40
    python -m shifu_tensorflow_tpu.obs tail    --journal ... --follow
    python -m shifu_tensorflow_tpu.obs trace 4f2a91b0c3d4e5f6 --journal ...
    python -m shifu_tensorflow_tpu.obs trace 0:3 --journal ...
    python -m shifu_tensorflow_tpu.obs top     --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs fleet   --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs compile --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs mem     --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs report  --journal /tmp/job.jsonl
    python -m shifu_tensorflow_tpu.obs diff /tmp/runA.jsonl /tmp/runB.jsonl
    python -m shifu_tensorflow_tpu.obs diff --bench
    python -m shifu_tensorflow_tpu.obs profile --journal ... --request \
        --dir /tmp/dump --seconds 5

Works on a finished or a RUNNING job: readers never lock writers, and a
torn final line (writer killed mid-event) is skipped, not fatal.  The
``--journal`` path is the base the job was configured with
(``shifu.tpu.obs-journal``); fleet-worker siblings (``.w<k>`` train,
``.s<k>`` serve) and rotations (``.N``) are discovered and merged by
``(ts, writer, seq)``.

``trace`` reconstructs ONE causal story: a request id (as minted at
serve ingress / supplied via ``X-Request-Id``) or one worker's epoch
(``worker:epoch``) across every plane that touched it — rendered on a
fleet-aligned clock when the writers stamped ``offset=`` estimates
(obs/fleet.ClockSync; ``--json`` keeps the raw wall clocks).  ``fleet``
renders the per-rank skew table and straggler excursions
(``straggler_detect``/``straggler_clear``) the coordinator journaled.  ``top`` is a
live terminal dashboard (``--once`` for CI) that tails the journals and
optionally scrapes ``/metrics`` URLs.  ``compile`` renders the compile
flight recorder's history (per-callable costs, signatures, recompile
storms — which signature churned and when the storm started and
cleared), ``mem`` the device-memory accountant's bucket split and
high-water marks, and ``profile`` lists journaled ``jax.profiler``
captures or (``--request``) asks the running fleet for one.  Every
reading subcommand takes ``--json`` for machine-readable output —
scripts and the autoscaling supervisor must not screen-scrape the
human renderer.

stdlib-only and jax-free: this must run on an operator's laptop against
a journal scp'd out of a dead fleet.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
import time
from collections import defaultdict

from shifu_tensorflow_tpu.obs.journal import journal_files, read_events
from shifu_tensorflow_tpu.obs.rollup import (
    read_rollups,
    reconstruct,
    rollup_files,
)

#: stable top-level schema tags on every machine-readable document, so
#: downstream tooling can detect format drift instead of guessing from
#: key shapes (pinned by test)
SUMMARY_SCHEMA = "stpu.obs.summary/1"
REPORT_SCHEMA = "stpu.obs.report/1"
DIFF_SCHEMA = "stpu.obs.diff/1"

#: events that are high-signal fleet lifecycle (the timeline keeps every
#: event, but these get rendered even under --compact aggregation)
_STEP_PHASES = ("infeed", "host", "dispatch", "block")

#: per-dispatch request records — high-volume, elided from the fleet
#: timeline (trace/top still read them)
_BULK_EVENTS = ("step_breakdown", "serve_batch")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shifu_tensorflow_tpu.obs",
        description="Inspect a shifu.tpu.obs-journal event journal.",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    tail = sub.add_parser("tail", help="print the last N events")
    tail.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    tail.add_argument("-n", type=int, default=20, dest="count",
                      help="events to show (default 20)")
    tail.add_argument("--json", action="store_true", dest="as_json",
                      help="raw events, one JSON object per line")
    tail.add_argument("--follow", "-f", action="store_true",
                      help="live-tail: keep polling the journals and "
                           "print events as they land (rotation-aware; "
                           "re-reads only growing files)")
    tail.add_argument("--interval", type=float, default=1.0,
                      help="--follow poll seconds (default 1)")

    summ = sub.add_parser(
        "summary",
        help="per-step time budget + serve plane + fleet event timeline",
    )
    summ.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    summ.add_argument("--timeline-limit", type=int, default=200,
                      help="max timeline rows (default 200; 0 = all)")
    summ.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable summary document")

    trace = sub.add_parser(
        "trace",
        help="causal timeline of one request (rid) or one step "
             "(worker:epoch) across every plane",
    )
    trace.add_argument("id",
                       help="a request correlation id (X-Request-Id / "
                            "minted rid), or worker:epoch (e.g. 0:3)")
    trace.add_argument("--journal", required=True,
                       help="journal base path (shifu.tpu.obs-journal)")
    trace.add_argument("--json", action="store_true", dest="as_json",
                       help="matching events, one JSON object per line")

    fleet = sub.add_parser(
        "fleet",
        help="per-rank skew table + straggler excursions (fleet_skew / "
             "straggler_detect / straggler_clear events)",
    )
    fleet.add_argument("--journal", required=True,
                       help="journal base path (shifu.tpu.obs-journal)")
    fleet.add_argument("--json", action="store_true", dest="as_json",
                       help="machine-readable fleet document")

    data = sub.add_parser(
        "data",
        help="per-feature train-baseline-vs-live-serve table, drift "
             "excursions (data_drift / data_drift_clear events)",
    )
    data.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    data.add_argument("--bundle", action="append", default=[],
                      dest="bundles",
                      help="an exported bundle dir (or a multi-tenant "
                           "models dir) whose feature_stats.json is the "
                           "train baseline (repeatable); without it the "
                           "baseline comes from journaled train-plane "
                           "data_stats events")
    data.add_argument("--features", type=int, default=20,
                      help="max feature rows per model, highest drift "
                           "score first (default 20; 0 = all)")
    data.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable data document")

    rep = sub.add_parser(
        "report",
        help="one-run operator report from the rotation-exempt rollup "
             "sidecars: totals, per-tenant cost, utilization, "
             "excursions — survives journal rotation",
    )
    rep.add_argument("--journal", required=True,
                     help="journal base path (shifu.tpu.obs-journal) or "
                          "one .rollup.jsonl sidecar")
    rep.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable report document")

    diff = sub.add_parser(
        "diff",
        help="compare two runs' rollup archives (noise-aware), or "
             "--bench: the last two BENCH_HISTORY.jsonl entries",
    )
    diff.add_argument("runs", nargs="*",
                      help="two journal bases (or .rollup.jsonl "
                           "sidecars); with --bench, at most one bench "
                           "name to filter the history by")
    diff.add_argument("--bench", action="store_true",
                      help="diff the last two BENCH_HISTORY.jsonl "
                           "entries of one bench instead of rollups")
    diff.add_argument("--history", default="BENCH_HISTORY.jsonl",
                      help="--bench history file "
                           "(default ./BENCH_HISTORY.jsonl)")
    diff.add_argument("--threshold", type=float, default=0.02,
                      help="relative-change floor below which a delta "
                           "is noise (default 0.02 = 2%%)")
    diff.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable diff document")

    comp = sub.add_parser(
        "compile",
        help="compile flight-recorder history: per-callable compile "
             "costs, signatures, and recompile-storm excursions",
    )
    comp.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    comp.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable compile document")

    mem = sub.add_parser(
        "mem",
        help="device-memory accounting: per-worker bucket split, "
             "high-water marks, per-model device bytes",
    )
    mem.add_argument("--journal", required=True,
                     help="journal base path (shifu.tpu.obs-journal)")
    mem.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable memory document")

    prof = sub.add_parser(
        "profile",
        help="list journaled jax.profiler captures, or --request one "
             "from the running fleet",
    )
    prof.add_argument("--journal", required=True,
                      help="journal base path (shifu.tpu.obs-journal)")
    prof.add_argument("--request", action="store_true",
                      help="write a capture trigger beside the journal; "
                           "the fleet's next obs tick starts the window")
    prof.add_argument("--dir", dest="out_dir",
                      help="dump directory for --request")
    prof.add_argument("--seconds", type=float, default=5.0,
                      help="capture window length for --request "
                           "(default 5)")
    prof.add_argument("--worker", type=int, default=None,
                      help="pin --request to one worker index "
                           "(default: first poller wins)")
    prof.add_argument("--json", action="store_true", dest="as_json",
                      help="capture events, one JSON object per line")

    score = sub.add_parser(
        "score",
        help="reconstruct a bulk scoring job from its journal: shard "
             "commit state, per-worker commits, lease reclaims/"
             "duplicates, row totals",
    )
    score.add_argument("--journal", required=True,
                       help="journal base path the score driver wrote "
                            "(`score run --journal ...`)")
    score.add_argument("--json", action="store_true", dest="as_json",
                       help="machine-readable score-job document")

    life = sub.add_parser(
        "lifecycle",
        help="reconstruct closed-loop model lifecycle cycles from the "
             "journal: trigger evidence, retrain, shadow, ramp steps, "
             "the promote/rollback verdict and its latency",
    )
    life.add_argument("--journal", required=True,
                      help="journal base path shared by the serve fleet "
                           "and the lifecycle controller (.l writer)")
    life.add_argument("--model", default=None,
                      help="only cycles managing this tenant")
    life.add_argument("--json", action="store_true", dest="as_json",
                      help="machine-readable lifecycle document")

    top = sub.add_parser(
        "top",
        help="live dashboard: tail the journals (+ optionally scrape "
             "/metrics) and render fleet state",
    )
    top.add_argument("--journal", required=True,
                     help="journal base path (shifu.tpu.obs-journal)")
    top.add_argument("--metrics-url", action="append", default=[],
                     dest="metrics_urls",
                     help="a /metrics URL to scrape each refresh "
                          "(repeatable); failures are tolerated — the "
                          "journal alone still renders")
    top.add_argument("--interval", type=float, default=2.0,
                     help="refresh seconds (default 2)")
    top.add_argument("--once", action="store_true",
                     help="render one frame and exit (CI / dead fleets)")
    return p


# ---- shared rendering ----

def _fmt_event(ev: dict, t0: float) -> str:
    ts = ev.get("ts", t0)
    plane = ev.get("plane", "?")
    worker = ev.get("worker")
    who = f"{plane} w{worker}" if worker is not None else plane
    # offset is the writer's clock stamp, not event payload — rendered
    # timelines use it for alignment (trace) or the fleet table; the
    # raw value stays in --json output
    skip = {"ts", "event", "plane", "worker", "seq", "job", "offset"}
    detail = " ".join(
        f"{k}={_short(v)}" for k, v in ev.items() if k not in skip
    )
    return f"+{ts - t0:10.3f}s  {who:<14} {ev.get('event', '?'):<22} {detail}"


def _short(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    s = str(v)
    return s if len(s) <= 60 else s[:57] + "..."


def cmd_tail(args) -> int:
    if getattr(args, "follow", False):
        return _tail_follow(args)
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    shown = events[-args.count:]
    if args.as_json:
        for ev in shown:
            print(json.dumps(ev, separators=(",", ":"), default=str))
        return 0
    t0 = events[0].get("ts", 0.0)
    for ev in shown:
        print(_fmt_event(ev, t0))
    return 0


def _event_key(ev: dict) -> tuple:
    """Identity of one journal event for follow-mode dedup: (ts, writer
    coordinates, seq) — unique per event by the Journal's contract (one
    monotonic seq per writer).  Bounded memory: the journal itself is
    rotation-bounded, so the set of live keys is too."""
    return (ev.get("ts", 0.0), ev.get("plane"), ev.get("worker"),
            ev.get("seq"), ev.get("event"))


def _tail_follow(args) -> int:
    """Live tail: poll the journal set, print what's new.  Reuses the
    read_events parse cache, so each poll re-parses only files whose
    (size, mtime, inode) changed — the growing active file, not the
    whole rotation set (satellite of the PR-7 `obs top` cache)."""
    cache: dict = {}
    seen: set = set()
    t0 = None
    first = True
    while True:
        events = read_events(args.journal, cache=cache)
        if events and t0 is None:
            t0 = events[0].get("ts", 0.0)
        new = [ev for ev in events if _event_key(ev) not in seen]
        if first:
            # start like plain tail: the last N events, then the stream
            new = new[-args.count:]
            seen.update(_event_key(ev) for ev in events)
            first = False
        else:
            seen.update(_event_key(ev) for ev in new)
            if events:
                # prune keys that rotated out of the journal set — the
                # seen-set tracks the live window, not the whole run
                min_ts = events[0].get("ts", 0.0)
                if len(seen) > 4 * len(events):
                    seen = {k for k in seen if k[0] >= min_ts}
        for ev in new:
            if args.as_json:
                print(json.dumps(ev, separators=(",", ":"), default=str),
                      flush=True)
            else:
                print(_fmt_event(ev, t0 or 0.0), flush=True)
        try:
            time.sleep(max(0.1, args.interval))
        except KeyboardInterrupt:
            return 0


# ---- step budget (data + renderer) ----

def _budget_data(events: list[dict]) -> dict:
    """Aggregate step_breakdown (+ matching epoch) events into one
    budget record per worker: where each step's wall clock went."""
    acc: dict = defaultdict(lambda: {
        "epochs": 0, "steps": 0,
        "infeed_wait": 0.0, "infeed_put": 0.0, "host_produce": 0.0,
        **{p: 0.0 for p in _STEP_PHASES}, "spans": defaultdict(
            lambda: {"count": 0, "total_s": 0.0}),
    })
    epoch_wall: dict = defaultdict(float)  # worker -> train wall seconds
    for ev in events:
        w = ev.get("worker", 0) or 0
        if ev.get("event") == "step_breakdown":
            a = acc[w]
            a["epochs"] += 1
            a["steps"] += int(ev.get("steps", 0))
            for p in _STEP_PHASES:
                a[p] += float(ev.get(f"{p}_s", 0.0))
            a["infeed_wait"] += float(ev.get("infeed_wait_s", 0.0))
            a["infeed_put"] += float(ev.get("infeed_put_s", 0.0))
            a["host_produce"] += float(ev.get("host_produce_s", 0.0))
            for name, s in (ev.get("spans") or {}).items():
                a["spans"][name]["count"] += int(s.get("count", 0))
                a["spans"][name]["total_s"] += float(s.get("total_s", 0.0))
        elif ev.get("event") == "epoch":
            epoch_wall[w] += float(ev.get("train_time_s", 0.0))
    workers = {}
    for w in sorted(acc):
        a = acc[w]
        phase_total = sum(a[p] for p in _STEP_PHASES)
        wall = epoch_wall.get(w, 0.0) or phase_total
        denom = max(wall, phase_total) or 1.0
        other = max(0.0, denom - phase_total)
        workers[w] = {
            "epochs": a["epochs"], "steps": a["steps"],
            "wall_s": round(denom, 6),
            "step_ms": round(denom / a["steps"] * 1000.0, 3)
            if a["steps"] else 0.0,
            "pct": {
                **{p: round(100.0 * a[p] / denom, 1)
                   for p in _STEP_PHASES},
                "other": round(100.0 * other / denom, 1),
            },
            "infeed_wait_pct": round(100.0 * a["infeed_wait"] / denom, 1),
            "infeed_put_pct": round(100.0 * a["infeed_put"] / denom, 1),
            "host_produce_pct": round(100.0 * a["host_produce"] / denom, 1),
            "spans": {k: dict(v) for k, v in sorted(a["spans"].items())},
        }
    return workers


def _render_budget(workers: dict) -> list[str]:
    if not workers:
        return ["  (no step_breakdown events — was the run traced? "
                "set shifu.tpu.obs-enabled=true / --obs)"]
    lines = [
        "  worker  epochs  steps  step_ms   infeed%   host%  dispatch%"
        "  block%  other%"
    ]
    for w, a in workers.items():
        pct = a["pct"]
        lines.append(
            f"  {w:<7} {a['epochs']:<7} {a['steps']:<6} {a['step_ms']:<9.3f}"
            f" {pct['infeed']:<9.1f} {pct['host']:<6.1f}"
            f" {pct['dispatch']:<10.1f} {pct['block']:<7.1f}"
            f" {pct['other']:.1f}"
        )
        if a["infeed_wait_pct"] or a["infeed_put_pct"] \
                or a["host_produce_pct"]:
            # pipelined infeed: wait is the consumer's stall (part of the
            # infeed%% above); put and host-produce are work on the put
            # thread, overlapped with dispatch — wait-heavy means STARVED
            # (widen the ingest pipeline), put-heavy means PLACEMENT-SLOW
            # (transfer/pad cost; see docs/ingest.md)
            line = (
                f"          infeed split: wait "
                f"{a['infeed_wait_pct']:.1f}% of wall, put "
                f"{a['infeed_put_pct']:.1f}% (overlapped)"
            )
            if a["host_produce_pct"]:
                line += (f", host produce "
                         f"{a['host_produce_pct']:.1f}%"
                         f" (overlapped)")
            lines.append(line)
        span_bits = [
            f"{name} {s['count']}x {s['total_s']:.3f}s"
            for name, s in a["spans"].items()
        ]
        if span_bits:
            lines.append(f"          spans: {', '.join(span_bits)}")
    return lines


# ---- serve plane (data + renderer) ----

def _serve_data(events: list[dict]) -> dict:
    """Aggregate the serve plane's lifecycle events: request volume and
    rate per scoring process, shed pressure, reload outcomes, and the
    fleet split — what the SO_REUSEPORT fleet's per-process /metrics
    cannot show in one place."""
    serve = [e for e in events if e.get("plane") == "serve"]
    if not serve:
        return {}
    per: dict = defaultdict(lambda: {
        "start_ts": None, "stop_ts": None, "requests": None,
        "reloads": 0, "refused": 0, "shed_events": 0, "shed_total": 0,
        # multi-tenant shed events carry per-TENANT counters: the
        # worker total is the SUM of per-model maxima, not a max
        # across tenants (which would report only the hottest one)
        "_shed_max": {},
    })
    fleet = {"workers": None, "restarts": 0}
    # autoscaler decisions (scale_up / scale_down / rebalance), in
    # journal order with their evidence — the dead-fleet reconstruction
    # of the supervisor's control loop
    autoscale: list = []
    # per-MODEL aggregation (multi-tenant serve: events carry a `model`
    # dimension) — rows/batches from serve_batch, tenancy lifecycle
    # from model_admit/model_evict/model_admit_failed.  Rows
    # materialize ONLY in branches that count something: an event kind
    # this table doesn't track must not mint an all-zero row that
    # reads as "present and idle".
    models: dict = defaultdict(lambda: {
        "rows": 0, "bucket_rows": 0, "batches": 0, "sheds": 0,
        "reloads": 0, "refused": 0, "admits": 0, "evicts": 0,
    })
    # shared dispatch lane lifecycle (lane_owner / lane_degraded /
    # lane_restored), in journal order — the dead-fleet reconstruction
    # of who owned fleet dispatch and when siblings fell back to
    # private dispatch
    lane: list = []

    def mm_of(ev):
        mname = ev.get("model")
        return models[mname] if mname else None

    for ev in serve:
        kind = ev.get("event")
        w = ev.get("worker")
        a = per[w]
        if kind == "serve_start":
            a["start_ts"] = ev.get("ts")
        elif kind == "serve_stop":
            a["stop_ts"] = ev.get("ts")
            a["requests"] = ev.get("requests_total")
            a["shed_total"] = max(a["shed_total"],
                                  int(ev.get("shed_total", 0) or 0))
        elif kind == "reload":
            a["reloads"] += 1
            mm = mm_of(ev)
            if mm is not None:
                mm["reloads"] += 1
        elif kind in ("reload_refused", "model_admit_failed"):
            a["refused"] += 1
            mm = mm_of(ev)
            if mm is not None:
                mm["refused"] += 1
        elif kind == "shed":
            a["shed_events"] += 1
            key = ev.get("model")
            a["_shed_max"][key] = max(
                a["_shed_max"].get(key, 0),
                int(ev.get("shed_total", 0) or 0))
            mm = mm_of(ev)
            if mm is not None:
                mm["sheds"] += 1
        elif kind == "serve_batch":
            mm = mm_of(ev)
            if mm is not None:
                mm["batches"] += 1
                mm["rows"] += int(ev.get("rows", 0) or 0)
                # bucket = rows the DEVICE paid (useful + ladder
                # padding); rows/bucket_rows is the occupancy column
                mm["bucket_rows"] += int(
                    ev.get("bucket", ev.get("rows", 0)) or 0)
        elif kind == "model_admit":
            mm = mm_of(ev)
            if mm is not None:
                mm["admits"] += 1
        elif kind == "model_evict":
            mm = mm_of(ev)
            if mm is not None:
                mm["evicts"] += 1
        elif kind == "serve_fleet_start":
            fleet["workers"] = ev.get("workers")
            fleet["workers_max"] = ev.get("workers_max")
            fleet["autoscale"] = ev.get("autoscale")
        elif kind in ("serve_worker_restart",):
            fleet["restarts"] += 1
        elif kind in ("scale_up", "scale_down", "rebalance"):
            autoscale.append({
                "action": kind,
                "ts": ev.get("ts"),
                "to_workers": ev.get("to_workers"),
                "model": ev.get("model"),
                "weight": ev.get("weight"),
                "reason": ev.get("reason"),
            })
        elif kind in ("lane_owner", "lane_degraded", "lane_restored"):
            lane.append({
                "event": kind,
                "ts": ev.get("ts"),
                "worker": w,
                "redispatched": ev.get("redispatched"),
                "connects": ev.get("connects"),
            })
    rows = {}
    for w, a in per.items():
        if (a["start_ts"] is None and a["requests"] is None
                and not a["reloads"] and not a["refused"]
                and not a["shed_events"]):
            continue
        rate = None
        if (a["requests"] is not None and a["start_ts"] is not None
                and a["stop_ts"] is not None
                and a["stop_ts"] > a["start_ts"]):
            rate = round(a["requests"] / (a["stop_ts"] - a["start_ts"]), 1)
        # the stop line's counter is the worker-wide aggregate; shed
        # events each carry one tenant's counter, so their per-model
        # maxima SUM to the worker total — take whichever saw more
        a["shed_total"] = max(a["shed_total"],
                              sum(a["_shed_max"].values()))
        rows[w] = {**{k: v for k, v in a.items()
                      if k not in ("start_ts", "stop_ts", "_shed_max")},
                   "req_per_s": rate}
    return {"fleet": fleet, "workers": rows,
            "models": {m: dict(v) for m, v in sorted(models.items())},
            "autoscale": autoscale, "lane": lane}


def _render_serve(data: dict) -> list[str]:
    if not data:
        return []
    fleet, rows = data["fleet"], data["workers"]
    models = data.get("models") or {}
    lines = []
    if fleet["workers"]:
        line = f"  fleet: {fleet['workers']} workers"
        if fleet.get("autoscale") and fleet.get("workers_max"):
            line += f" (autoscaling up to {fleet['workers_max']})"
        if fleet["restarts"]:
            line += f", {fleet['restarts']} restart(s)"
        lines.append(line)
    for d in data.get("autoscale") or []:
        if d["action"] == "rebalance":
            what = (f"tenant {d['model']} weight -> {d['weight']:g}"
                    if d.get("weight") is not None else "weights")
        else:
            what = f"-> {d['to_workers']} workers"
        lines.append(f"  autoscale: {d['action']} {what}"
                     + (f"  ({d['reason']})" if d.get("reason") else ""))
    for d in data.get("lane") or []:
        # shared dispatch-lane lifecycle in journal order: owner bind,
        # sibling joins, degradations — who owned fleet dispatch when
        who = "-" if d.get("worker") is None else str(d["worker"])
        if d["event"] == "lane_owner":
            what = "owns the fleet dispatch lane"
        elif d["event"] == "lane_restored":
            what = (f"joined the lane (connect "
                    f"#{d.get('connects') or '?'})")
        else:
            what = (f"lane degraded -> private dispatch "
                    f"({d.get('redispatched') or 0} in-flight "
                    f"re-dispatched)")
        lines.append(f"  lane: worker {who} {what}")
    if not rows:
        # a fleet whose workers all died before serve_start (crash
        # loop: bad artifact, stolen port) has no per-worker rows, but
        # the fleet line above — workers + restart count — is exactly
        # what the operator diagnosing it needs; never hide it
        if fleet["workers"]:
            lines.append("  (no worker reached serve_start)")
        return lines
    lines.append(
        "  worker  requests  req/s    shed   reloads  refused")
    for w in sorted(rows, key=lambda k: (k is None, k)):
        a = rows[w]
        who = "-" if w is None else str(w)
        reqs = a["requests"]
        rate = "" if a["req_per_s"] is None else f"{a['req_per_s']}"
        lines.append(
            f"  {who:<7} {('?' if reqs is None else reqs):<9} "
            f"{rate or '?':<8} {a['shed_total']:<6} {a['reloads']:<8} "
            f"{a['refused']}"
        )
    if models:
        # the multi-tenant split: which model the rows/sheds/tenancy
        # churn belong to — journal-only (the per-process /metrics
        # can't aggregate a fleet; this table can)
        lines.append(
            "  model          rows     batches  shed-ev  reloads  "
            "refused  admits  evicts  occup")
        for m, v in models.items():
            # useful rows / device (bucket) rows across this model's
            # journaled dispatches — the fleet-coalescing health number
            # (fragmented fleets pad more, so this falls)
            occ = (f"{v['rows'] / v['bucket_rows']:.3f}"
                   if v.get("bucket_rows") else "-")
            lines.append(
                f"  {m:<14} {v['rows']:<8} {v['batches']:<8} "
                f"{v['sheds']:<8} {v['reloads']:<8} {v['refused']:<8} "
                f"{v['admits']:<7} {v['evicts']:<7} {occ}"
            )
    return lines


# ---- slo plane (data + renderer) ----

def _slo_data(events: list[dict]) -> dict:
    """Last-known SLO state per signal from the journaled breach /
    recover / anomaly transitions (obs/slo.py)."""
    signals: dict = {}
    for ev in events:
        kind = ev.get("event")
        if kind not in ("slo_breach", "slo_recover", "slo_anomaly"):
            continue
        name = ev.get("signal", "?")
        s = signals.setdefault(name, {
            "breaches": 0, "recovers": 0, "anomalies": 0,
            "breached": False, "last_value": None, "target": None,
            "last_ts": None, "worker": ev.get("worker"),
        })
        s["last_ts"] = ev.get("ts")
        s["last_value"] = ev.get("value")
        if kind == "slo_breach":
            s["breaches"] += 1
            s["breached"] = True
            s["target"] = ev.get("target")
            s["window"] = ev.get("window")
        elif kind == "slo_recover":
            s["recovers"] += 1
            s["breached"] = False
            s["target"] = ev.get("target")
            s["breach_s"] = ev.get("breach_s")
        else:
            s["anomalies"] += 1
            s["last_z"] = ev.get("z")
    return signals


def _render_slo(signals: dict, t0: float) -> list[str]:
    if not signals:
        return []
    lines = ["  signal            state      value      target   "
             "breaches  anomalies"]
    for name in sorted(signals):
        s = signals[name]
        state = "BREACHED" if s["breached"] else "ok"
        val = "?" if s["last_value"] is None else f"{s['last_value']:.4g}"
        tgt = "-" if not s.get("target") else f"{s['target']:.4g}"
        lines.append(
            f"  {name:<17} {state:<10} {val:<10} {tgt:<8} "
            f"{s['breaches']:<9} {s['anomalies']}"
        )
    return lines


# ---- summary ----

def _build_summary(base: str, cache: dict | None = None) -> dict | None:
    files = journal_files(base)
    events = read_events(base, cache=cache)
    if not events:
        return None
    t0 = events[0].get("ts", 0.0)
    t1 = events[-1].get("ts", t0)
    counts: dict = defaultdict(int)
    for ev in events:
        counts[ev.get("event", "?")] += 1
    return {
        "schema": SUMMARY_SCHEMA,
        "journal": base,
        "files": files,
        "events": len(events),
        "t0": t0,
        "t1": t1,
        "span_s": round(t1 - t0, 3),
        "jobs": sorted({e["job"] for e in events if "job" in e}),
        "counts": dict(sorted(counts.items())),
        "budget": _budget_data(events),
        "serve": _serve_data(events),
        "slo": _slo_data(events),
        "fleet": _fleet_data(events),
        "data": _data_summary(events),
        "mesh": _mesh_data(events),
        "_events": events,  # stripped before --json output
    }


def _mesh_data(events: list[dict]) -> dict:
    """The fleet's resolved device-mesh layout: the LAST ``mesh`` event
    per worker (each worker journals one at start; a fleet restart's
    re-journal supersedes) — rendered as one summary line so an
    operator reads the data×model split without grepping the journal."""
    per_worker: dict = {}
    for ev in events:
        if ev.get("event") != "mesh":
            continue
        per_worker[ev.get("worker")] = {
            "shape": ev.get("shape"),
            "coord": ev.get("coord"),
            "fingerprint": ev.get("fingerprint"),
            "devices": ev.get("devices"),
        }
    if not per_worker:
        return {}
    any_rec = next(iter(per_worker.values()))
    return {
        "shape": any_rec.get("shape"),
        "fingerprint": any_rec.get("fingerprint"),
        "devices": any_rec.get("devices"),
        "workers": {
            str(w): rec.get("coord")
            for w, rec in sorted(
                per_worker.items(), key=lambda kv: str(kv[0]))
        },
    }


def _render_mesh(m: dict) -> list[str]:
    if not m:
        return []
    shape = m.get("shape") or {}
    spec = ",".join(f"{n}:{s}" for n, s in shape.items()) or "?"
    line = (f"  mesh {spec} ({m.get('devices', '?')} device(s), "
            f"fingerprint {m.get('fingerprint', '?')})")
    coords = {w: c for w, c in (m.get("workers") or {}).items()
              if c is not None}
    out = [line]
    if coords:
        out.append("  rank coordinates: " + ", ".join(
            f"{w}→({', '.join(f'{k}={v}' for k, v in c.items())})"
            for w, c in sorted(coords.items())))
    return out


def _data_summary(events: list[dict]) -> dict:
    """The data leg's compact summary (the full per-feature table is
    ``obs data``'s job): per-model live rows + drift score, train
    sketch presence, excursion counts."""
    d = _data_data(events)
    if not d:
        return {}
    return {
        "train_workers": sorted(d["train"], key=lambda w: (w is None, w)),
        "models": {
            m: {
                "live_rows": v["stats"].get("rows"),
                "drift_score": v.get("drift_score"),
                "drifting": v.get("drifting") or 0,
            }
            for m, v in d["serve"].items()
        },
        "excursions": len(d["excursions"]),
        "open_excursions": sum(
            1 for e in d["excursions"] if e["clear_ts"] is None),
    }


def _render_data_brief(d: dict) -> list[str]:
    if not d:
        return []
    lines = []
    for m, v in sorted(d["models"].items()):
        score = v.get("drift_score")
        lines.append(
            f"  model {m}: live {v['live_rows']} rows"
            + (f", drift score {score:.3g}" if score is not None else "")
            + (f", {v['drifting']} feature(s) DRIFTING"
               if v["drifting"] else "")
        )
    if d["train_workers"]:
        lines.append(f"  train sketches from worker(s) "
                     f"{d['train_workers']}")
    lines.append(f"  drift excursions: {d['excursions']} "
                 f"({d['open_excursions']} open)  — `obs data` for the "
                 f"per-feature table")
    return lines


def cmd_summary(args) -> int:
    data = _build_summary(args.journal)
    if data is None:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    events = data.pop("_events")
    if args.as_json:
        print(json.dumps(data, indent=2, default=str))
        return 0
    t0 = data["t0"]
    print(f"journal {args.journal}: {data['events']} events in "
          f"{len(data['files'])} file(s), spanning {data['span_s']:.1f}s"
          + (f"  [job {', '.join(data['jobs'])}]" if data["jobs"] else ""))
    print("  " + ", ".join(
        f"{name} x{n}" for name, n in data["counts"].items()))
    print()
    mesh_lines = _render_mesh(data.get("mesh") or {})
    if mesh_lines:
        print("device mesh")
        for line in mesh_lines:
            print(line)
        print()
    print("per-step time budget")
    for line in _render_budget(data["budget"]):
        print(line)
    print()
    serve_lines = _render_serve(data["serve"])
    if serve_lines:
        print("serve plane")
        for line in serve_lines:
            print(line)
        print()
    slo_lines = _render_slo(data["slo"], t0)
    if slo_lines:
        print("slo")
        for line in slo_lines:
            print(line)
        print()
    fleet_lines = _render_fleet(data["fleet"], t0)
    if fleet_lines:
        print("fleet skew")
        for line in fleet_lines:
            print(line)
        print()
    data_lines = _render_data_brief(data["data"])
    if data_lines:
        print("data plane")
        for line in data_lines:
            print(line)
        print()
    print("fleet timeline")
    timeline = [e for e in events if e.get("event") not in _BULK_EVENTS]
    limit = args.timeline_limit
    shown = timeline if not limit else timeline[-limit:]
    if len(shown) < len(timeline):
        print(f"  ... {len(timeline) - len(shown)} earlier events elided "
              f"(--timeline-limit {limit})")
    for ev in shown:
        print(" " + _fmt_event(ev, t0))
    return 0


# ---- trace ----

_COORD_RE = re.compile(r"^(\d+):(\d+)$")


def _match_rid(ev: dict, rid: str) -> bool:
    if ev.get("rid") == rid:
        return True
    rids = ev.get("rids")
    return isinstance(rids, list) and rid in rids


def _match_step(ev: dict, worker: int, epoch: int) -> bool:
    if ev.get("epoch") != epoch:
        return False
    w = ev.get("worker")
    # coordinator-plane records of the same epoch (epoch_summary,
    # rollback directives) carry no worker, or the arbitrating one —
    # they belong to every worker's story for that epoch
    return w is None or w == worker or ev.get("plane") == "coordinator"


def cmd_trace(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r}", file=sys.stderr)
        return 1
    m = _COORD_RE.match(args.id)
    if m:
        worker, epoch = int(m.group(1)), int(m.group(2))
        sel = [e for e in events if _match_step(e, worker, epoch)]
        what = f"worker {worker} epoch {epoch}"
        if not sel:
            # the serve sanitizer strips ':' from rids, but journals
            # written by older builds (or by hand) may carry one — a
            # missed worker:epoch query falls back to a rid match
            # rather than refusing an id that is demonstrably present
            sel = [e for e in events if _match_rid(e, args.id)]
            if sel:
                what = f"rid {args.id}"
    else:
        sel = [e for e in events if _match_rid(e, args.id)]
        what = f"rid {args.id}"
    if not sel:
        print(f"no events for {what} under {args.journal!r} "
              f"(is the journal's rotation window past it?)",
              file=sys.stderr)
        return 1
    if args.as_json:
        # raw wall clocks preserved: each event keeps its writer's ts
        # (and its offset= estimate, when stamped) untouched
        for ev in sel:
            print(json.dumps(ev, separators=(",", ":"), default=str))
        return 0
    # offset-aligned rendering: workers stamp offset= (coordinator clock
    # minus theirs, obs/fleet.ClockSync), so ts + offset maps every
    # writer onto ONE clock — the merged timeline then reflects
    # causality across machines, not whose wall clock ran fast.  Events
    # without a stamp (the coordinator's own, pre-offset builds) align
    # at offset 0.
    aligned = any(e.get("offset") for e in sel)
    if aligned:
        sel = sorted(
            sel, key=lambda e: (e.get("ts", 0.0)
                                + float(e.get("offset", 0.0) or 0.0)))
    t0 = sel[0].get("ts", 0.0) + float(sel[0].get("offset", 0.0) or 0.0)
    planes = sorted({e.get("plane", "?") for e in sel})
    jobs = sorted({e["job"] for e in sel if "job" in e})
    print(f"trace {what}: {len(sel)} event(s) across "
          f"plane(s) {', '.join(planes)}"
          + (f"  [job {', '.join(jobs)}]" if jobs else ""))
    if aligned:
        print("  (timestamps offset-aligned to the coordinator clock; "
              "--json keeps raw wall clocks)")
    for ev in sel:
        if aligned and ev.get("offset"):
            ev = {**ev, "ts": (ev.get("ts", 0.0)
                               + float(ev.get("offset") or 0.0))}
            ev.pop("offset", None)
        print(" " + _fmt_event(ev, t0))
    # the request's phase split, when a serve_batch dispatch carried it
    for ev in sel:
        if ev.get("event") == "serve_batch":
            print(f"  -> coalesced into a {ev.get('rows', '?')}-row "
                  f"dispatch (bucket {ev.get('bucket', '?')}, "
                  f"{ev.get('requests', '?')} request(s)): waited "
                  f"{ev.get('queue_delay_s', 0.0):.4f}s, device "
                  f"{ev.get('dispatch_s', 0.0):.4f}s")
    return 0


# ---- rollup report (data + renderer) ----

def _report_doc(base: str) -> dict | None:
    """One-run document reconstructed from the rotation-exempt rollup
    sidecars alone — no journal read, so it survives rotation AND runs
    against a single scp'd ``.rollup.jsonl``."""
    records = read_rollups(base)
    if not records:
        return None
    doc = reconstruct(records)
    doc["schema"] = REPORT_SCHEMA
    doc["journal"] = base
    doc["files"] = ([base] if base.endswith(".rollup.jsonl")
                    else rollup_files(base))
    return doc


def _tenant_cost_table(doc: dict) -> dict[str, dict]:
    """Per-tenant cost rows: device-seconds / padded-row-seconds / rows
    / bytes from the cost leg's counters (exact — counter deltas), with
    the journal-fold serve volume as the fallback when the cost leg was
    off, plus request/shed counts from the serve counters."""
    counters = doc.get("counters") or {}
    cost = counters.get("cost") or {}
    serve_c = counters.get("serve") or {}
    fold = doc.get("serve") or {}
    models: set[str] = set(fold)
    for k in cost:
        if ":" in k and not k.startswith("train_"):
            models.add(k.split(":", 1)[1])
    for k in serve_c:
        if ":" in k:
            models.add(k.split(":", 1)[1])
    out: dict[str, dict] = {}
    for m in sorted(models):
        f = fold.get(m) or {}
        out[m] = {
            "device_s": round(cost.get(f"device_seconds:{m}",
                                       f.get("dispatch_s", 0.0)), 6),
            "padded_row_s": round(
                cost.get(f"padded_row_seconds:{m}", 0.0), 3),
            "rows": int(cost.get(f"rows:{m}", f.get("rows", 0))),
            "bytes": int(cost.get(f"bytes:{m}", 0)),
            "requests": int(serve_c.get(f"requests_total:{m}",
                                        f.get("requests", 0))),
            "shed": int(serve_c.get(f"shed_total:{m}", 0)),
        }
    total_dev = sum(r["device_s"] for r in out.values())
    for r in out.values():
        r["share_pct"] = (round(100.0 * r["device_s"] / total_dev, 1)
                          if total_dev else 0.0)
    return out


def _lane_utilization(doc: dict) -> dict | None:
    """Device-lane busy wall vs the run's wall clock.  With several
    serve workers each lane contributes its own busy seconds, so the
    fraction is lane-seconds per wall-second (can exceed 1)."""
    cost = (doc.get("counters") or {}).get("cost") or {}
    busy = cost.get("device_busy_seconds")
    if busy is None:
        return None
    span = (doc.get("t1") or 0.0) - (doc.get("t0") or 0.0)
    out = {"busy_s": round(float(busy), 3)}
    if span > 0:
        out["wall_s"] = round(span, 3)
        out["busy_frac"] = round(float(busy) / span, 4)
        out["idle_frac"] = round(max(0.0, 1.0 - float(busy) / span), 4)
    return out


def _fmt_excursion(e: dict, t0: float) -> str:
    start = e.get("start_ts")
    start_s = "?" if start is None else f"+{start - t0:.1f}s"
    end = e.get("end_ts")
    if end is not None:
        dur = "" if start is None else f" ({end - start:.1f}s)"
        span = f"{start_s} .. +{end - t0:.1f}s{dur}"
    else:
        span = f"{start_s} .. STILL OPEN"
    writer = f"  [{e['writer']}]" if e.get("writer") else ""
    return (f"  {e.get('kind', '?'):<11} {e.get('name', '?'):<24} "
            f"{span}{writer}")


def _render_report(doc: dict) -> list[str]:
    lines: list[str] = []
    t0 = doc.get("t0") or 0.0
    span = (doc.get("t1") or t0) - t0
    lines.append(
        f"run: {doc['windows']} rollup window(s) spanning {span:.1f}s, "
        f"writer(s) {', '.join(doc['writers']) or '?'}"
        + (f"  [job {', '.join(doc['jobs'])}]" if doc["jobs"] else ""))
    serve_c = (doc.get("counters") or {}).get("serve") or {}
    base_c = {k: v for k, v in serve_c.items() if ":" not in k}
    if base_c:
        order = ("requests_total", "rows_total", "batches_total",
                 "shed_total", "errors_total", "nan_rows_total")
        bits = [f"{k.removesuffix('_total')} {int(base_c[k])}"
                for k in order if base_c.get(k)]
        bits += [f"{k} {int(v)}" for k, v in sorted(base_c.items())
                 if k not in order and v]
        lines.append("totals (monotonic counters): " + ", ".join(bits))
    util = _lane_utilization(doc)
    if util is not None and "busy_frac" in util:
        lines.append(
            f"device lane: busy {util['busy_s']:.1f}s of "
            f"{util['wall_s']:.1f}s wall — utilization "
            f"{100 * util['busy_frac']:.1f}%, idle headroom "
            f"{100 * util['idle_frac']:.1f}%")
    tenants = _tenant_cost_table(doc)
    if tenants:
        lines.append("per-tenant cost (device attribution)")
        lines.append(
            "  model          device_s  share%  padded_row_s  rows"
            "      requests  shed    bytes")
        for m, r in tenants.items():
            lines.append(
                f"  {m:<14} {r['device_s']:<9.3f} {r['share_pct']:<7} "
                f"{r['padded_row_s']:<13.1f} {r['rows']:<9} "
                f"{r['requests']:<9} {r['shed']:<7} {_fmt_bytes(r['bytes'])}"
            )
    cost_c = (doc.get("counters") or {}).get("cost") or {}
    train_rows = {k.split(":w", 1)[1]: v for k, v in cost_c.items()
                  if k.startswith("train_device_seconds:w")}
    train_fold = doc.get("train") or {}
    if train_rows or train_fold:
        lines.append("train device time")
        lines.append("  worker  device_s   steps     epochs")
        workers = sorted(set(train_rows) | set(train_fold),
                         key=lambda w: (not w.isdigit(),
                                        int(w) if w.isdigit() else w))
        for w in workers:
            f = train_fold.get(w) or {}
            dev = train_rows.get(w, f.get("dispatch_s", 0.0))
            steps = int(cost_c.get(f"train_steps:w{w}",
                                   f.get("steps", 0)))
            lines.append(f"  {w:<7} {dev:<10.3f} {steps:<9} "
                         f"{int(f.get('epochs', 0))}")
    digests = doc.get("digests") or {}
    if digests:
        lines.append("windowed digests (count-weight merged)")
        lines.append("  signal                 stat   value      mean"
                     "       max        count")
        for sig in sorted(digests):
            s = digests[sig]
            stat = s.get("stat") or "mean"
            val = s.get(stat)
            lines.append(
                f"  {sig:<22} {stat:<6} "
                f"{'?' if val is None else f'{val:.4g}':<10} "
                f"{s.get('mean', 0.0):<10.4g} {s.get('max', 0.0):<10.4g} "
                f"{s['count']}")
    comp = doc.get("compile") or {}
    gauges = doc.get("gauges") or {}
    if comp or gauges:
        bits = []
        if comp:
            bits.append(f"{int(comp.get('compiles', 0))} compile(s), "
                        f"{comp.get('compile_s', 0.0):.2f}s total, "
                        f"max {comp.get('max_s', 0.0):.2f}s")
            if comp.get("aot_loads"):
                # the dead-fleet report says what admission actually
                # did: deserialized shipped executables vs fallbacks
                bits.append(
                    f"{int(comp['aot_loads'])} AOT load(s)"
                    + (f", {int(comp['aot_fallbacks'])} fallback(s)"
                       if comp.get("aot_fallbacks") else ""))
        if gauges.get("total_bytes"):
            bits.append(
                f"devmem high-water {_fmt_bytes(gauges['total_bytes'])}"
                + (f" ({100 * gauges['devmem_frac']:.1f}% of limit)"
                   if gauges.get("devmem_frac") else ""))
        lines.append("device/compiler: " + "; ".join(bits))
    excs = (doc.get("excursions") or []) + (doc.get("open_excursions")
                                            or [])
    if excs:
        lines.append("excursions")
        for e in excs:
            lines.append(_fmt_excursion(e, t0))
    else:
        lines.append("no excursions")
    return lines


def cmd_report(args) -> int:
    doc = _report_doc(args.journal)
    if doc is None:
        print(f"no rollup records under {args.journal!r} "
              f"(files: {rollup_files(args.journal) or 'none'}) — "
              "rollups write beside the journal once obs is enabled "
              "(shifu.tpu.obs-rollup, on by default with a journal)",
              file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
        return 0
    print(f"rollup report — {args.journal}")
    for line in _render_report(doc):
        print(line)
    return 0


# ---- cross-run diff ----

#: noise-discount scale for count-backed metrics (the datastats ~3/√n
#: small-sample discipline): a delta must clear k/√min(n) on top of the
#: relative floor before it can be called significant
_DIFF_NOISE_K = 3.0


def _delta_row(metric: str, va: float, vb: float, na: int, nb: int,
               floor: float, lower_is_better: bool) -> dict:
    rel = (vb - va) / abs(va)
    n = min(na or 0, nb or 0)
    noise = _DIFF_NOISE_K / math.sqrt(n) if n > 0 else 0.0
    bar = max(floor, noise)
    significant = abs(rel) >= bar
    worse = (rel > 0) == lower_is_better
    verdict = ("~same" if not significant
               else ("REGRESSED" if worse else "improved"))
    return {
        "metric": metric,
        "a": round(va, 6), "b": round(vb, 6),
        "delta_pct": round(100.0 * rel, 2),
        "count_a": na, "count_b": nb,
        "noise_floor_pct": round(100.0 * bar, 2),
        "significant": significant,
        "verdict": verdict,
    }


def _diff_rows(a: dict, b: dict, floor: float) -> list[dict]:
    rows: list[dict] = []
    da, db = a.get("digests") or {}, b.get("digests") or {}
    for sig in sorted(set(da) & set(db)):
        sa, sb = da[sig], db[sig]
        stat = sb.get("stat") or sa.get("stat") or "mean"
        va, vb = sa.get(stat), sb.get(stat)
        if va is None or vb is None or va <= 0:
            continue
        rows.append(_delta_row(
            f"{sig}.{stat}", va, vb, int(sa.get("count", 0)),
            int(sb.get("count", 0)), floor,
            # every digest-backed signal here is a latency/time/ratio:
            # smaller is better
            lower_is_better=True))

    def rate_of(doc, key):
        c = (doc.get("counters") or {}).get("serve") or {}
        span = (doc.get("t1") or 0.0) - (doc.get("t0") or 0.0)
        v = c.get(key)
        if not v or span <= 0:
            return None, 0
        return float(v) / span, int(v)

    for key, label in (("requests_total", "serve_requests_per_s"),
                       ("rows_total", "serve_rows_per_s")):
        (ra, na), (rb, nb) = rate_of(a, key), rate_of(b, key)
        if ra and rb:
            rows.append(_delta_row(label, ra, rb, na, nb, floor,
                                   lower_is_better=False))

    def cost_per_krow(doc):
        cost = (doc.get("counters") or {}).get("cost") or {}
        dev = sum(v for k, v in cost.items()
                  if k.startswith("device_seconds:"))
        n = sum(v for k, v in cost.items() if k.startswith("rows:"))
        return (dev / n * 1000.0, int(n)) if n else (None, 0)

    (ca, na), (cb, nb) = cost_per_krow(a), cost_per_krow(b)
    if ca and cb:
        rows.append(_delta_row("device_s_per_krow", ca, cb, na, nb,
                               floor, lower_is_better=True))
    return rows


def _diff_runs(args) -> int:
    if len(args.runs) != 2:
        print("obs diff needs exactly two runs (journal bases or "
              ".rollup.jsonl sidecars), or --bench", file=sys.stderr)
        return 2
    docs = []
    for run in args.runs:
        records = read_rollups(run)
        if not records:
            print(f"no rollup records under {run!r}", file=sys.stderr)
            return 1
        docs.append(reconstruct(records))
    a, b = docs
    rows = _diff_rows(a, b, args.threshold)
    doc = {
        "schema": DIFF_SCHEMA,
        "mode": "rollup",
        "a": {"run": args.runs[0], "t0": a.get("t0"), "t1": a.get("t1"),
              "windows": a.get("windows"), "jobs": a.get("jobs")},
        "b": {"run": args.runs[1], "t0": b.get("t0"), "t1": b.get("t1"),
              "windows": b.get("windows"), "jobs": b.get("jobs")},
        "metrics": rows,
        "regressions": [r["metric"] for r in rows
                        if r["verdict"] == "REGRESSED"],
    }
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
        return 0
    print(f"rollup diff — A: {args.runs[0]}  vs  B: {args.runs[1]}")
    if not rows:
        print("  no comparable metrics (both runs need rollup digests "
              "or counters)")
        return 1
    print("  metric                     A          B          Δ%        "
          "noise%   verdict")
    for r in rows:
        print(f"  {r['metric']:<26} {r['a']:<10.4g} {r['b']:<10.4g} "
              f"{r['delta_pct']:<+10.2f} {r['noise_floor_pct']:<8.2f} "
              f"{r['verdict']}")
    if doc["regressions"]:
        print(f"  REGRESSED: {', '.join(doc['regressions'])}")
    return 0


def _diff_bench(args) -> int:
    entries: list[dict] = []
    try:
        with open(args.history) as f:
            for raw in f:
                try:
                    e = json.loads(raw)
                except ValueError:
                    continue
                if isinstance(e, dict) and e.get("name"):
                    entries.append(e)
    except OSError:
        print(f"no bench history at {args.history!r} — `python bench.py "
              "<name>` appends one line per run", file=sys.stderr)
        return 1
    # failed runs (rc != 0) carry no trustworthy metrics — they stay in
    # the history as the record of the failure, but a diff must compare
    # two runs that actually measured something
    entries = [e for e in entries if not e.get("rc")]
    name = args.runs[0] if args.runs else None
    if name is None and entries:
        name = entries[-1]["name"]
    entries = [e for e in entries if e.get("name") == name]
    if len(entries) < 2:
        print(f"need at least two {name!r} entries in {args.history!r} "
              f"to diff (have {len(entries)})", file=sys.stderr)
        return 1
    a, b = entries[-2], entries[-1]
    rows = []
    ma, mb = a.get("metrics") or {}, b.get("metrics") or {}
    for k in sorted(set(ma) & set(mb)):
        va, vb = ma[k], mb[k]
        if (isinstance(va, (int, float)) and isinstance(vb, (int, float))
                and not isinstance(va, bool) and va):
            rel = (vb - va) / abs(va)
            rows.append({
                "metric": k, "a": va, "b": vb,
                "delta_pct": round(100.0 * rel, 2),
                "significant": abs(rel) >= args.threshold,
            })
    doc = {
        "schema": DIFF_SCHEMA,
        "mode": "bench",
        "name": name,
        "a": {k: a.get(k) for k in ("ts", "host", "artifact")},
        "b": {k: b.get(k) for k in ("ts", "host", "artifact")},
        "metrics": rows,
    }
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
        return 0
    host_a = (a.get("host") or {}).get("hostname", "?")
    host_b = (b.get("host") or {}).get("hostname", "?")
    print(f"bench diff — {name}: {a.get('ts')} ({host_a}) -> "
          f"{b.get('ts')} ({host_b})")
    if not rows:
        print("  no shared numeric metrics between the two entries")
        return 1
    for r in rows:
        mark = "  *" if r["significant"] else ""
        print(f"  {r['metric']:<34} {r['a']:<12.6g} -> {r['b']:<12.6g} "
              f"({r['delta_pct']:+.2f}%){mark}")
    return 0


def cmd_diff(args) -> int:
    if args.bench:
        return _diff_bench(args)
    return _diff_runs(args)


# ---- fleet skew (data + renderer) ----

def _fleet_data(events: list[dict]) -> dict:
    """Per-rank skew state + straggler excursions from the coordinator's
    ``fleet_skew`` / ``straggler_detect`` / ``straggler_clear`` events,
    plus the per-epoch ``comm`` drains, standby-promotion takeovers
    (``standby_promote`` / ``standby_claim``) and elastic re-splits —
    entirely from journal files, so a dead fleet's straggler AND
    takeover story reconstructs on a jax-free laptop."""
    ranks: dict = {}
    excursions: list[dict] = []
    open_exc: dict = {}
    comm: dict = defaultdict(lambda: {"calls": 0, "bytes": 0})
    promotions: list[dict] = []
    resplits: list[dict] = []
    standbys: set = set()
    epochs = 0
    straggler = None
    max_skew = None
    for ev in events:
        kind = ev.get("event")
        if kind == "fleet_skew":
            epochs += 1
            straggler = ev.get("straggler")
            max_skew = ev.get("max_skew")
            for w, r in (ev.get("ranks") or {}).items():
                ranks[w] = dict(r)
        elif kind == "straggler_detect":
            rec = {
                "worker": ev.get("worker"),
                "phase": ev.get("phase"),
                "skew": ev.get("skew"),
                "step_s": ev.get("step_s"),
                "fleet_step_s": ev.get("fleet_step_s"),
                "detect_ts": ev.get("ts"),
                "detect_epoch": ev.get("epoch"),
                "clear_ts": None,
                "clear_epoch": None,
                "straggler_s": None,
            }
            excursions.append(rec)
            open_exc[ev.get("worker")] = rec
        elif kind == "straggler_clear":
            rec = open_exc.pop(ev.get("worker"), None)
            if rec is not None:
                rec["clear_ts"] = ev.get("ts")
                rec["clear_epoch"] = ev.get("epoch")
                rec["straggler_s"] = ev.get("straggler_s")
        elif kind == "comm":
            for k, v in (ev.get("kinds") or {}).items():
                comm[k]["calls"] += int(v.get("calls", 0) or 0)
                comm[k]["bytes"] += int(v.get("bytes", 0) or 0)
        elif kind == "standby_register":
            standbys.add(ev.get("worker_id"))
        elif kind == "standby_promote":
            promotions.append({
                "worker": ev.get("worker"),
                "standby_id": ev.get("worker_id"),
                "old_id": ev.get("old_worker_id"),
                "epoch": ev.get("epoch"),
                "why": ev.get("why"),
                "hb_age_s": ev.get("hb_age_s"),
                "promote_ts": ev.get("ts"),
                "latency_s": None,
            })
        elif kind == "standby_claim":
            for p in reversed(promotions):
                if (p["standby_id"] == ev.get("worker_id")
                        and p["latency_s"] is None):
                    p["latency_s"] = ev.get("latency_s")
                    break
        elif kind == "resplit":
            resplits.append({
                "split_generation": ev.get("split_generation"),
                "ranks": ev.get("ranks"),
                "n_files": ev.get("n_files"),
                "why": ev.get("why"),
                "ts": ev.get("ts"),
            })
    if (not ranks and not excursions and not comm and not promotions
            and not resplits and not standbys):
        return {}
    def rank_key(kv):
        # ranks are JSON string keys: numeric order, not "0,1,10,11,2"
        try:
            return (0, int(kv[0]))
        except (TypeError, ValueError):
            return (1, kv[0])

    return {
        "ranks": dict(sorted(ranks.items(), key=rank_key)),
        "excursions": excursions,
        "epochs": epochs,
        "straggler": straggler,
        "max_skew": max_skew,
        "comm": {k: dict(v) for k, v in sorted(comm.items())},
        "standbys": sorted(s for s in standbys if s),
        "promotions": promotions,
        "resplits": resplits,
    }


def _render_fleet(data: dict, t0: float) -> list[str]:
    if not data:
        return []
    lines = []
    if data["ranks"]:
        lines.append(
            "  rank  step_s    skew    phase      barrier_s  offset_s"
            "    state")
        for w, r in data["ranks"].items():
            state = "STRAGGLER" if r.get("straggler") else "ok"
            barrier = r.get("barrier_s")
            offset = r.get("offset_s")
            lines.append(
                f"  {w:<5} {r.get('step_s', 0.0):<9.4f} "
                f"{r.get('skew', 1.0):<7.3f} "
                f"{r.get('phase', '?'):<10} "
                f"{('-' if barrier is None else f'{barrier:.4f}'):<10} "
                f"{('-' if offset is None else f'{offset:+.6f}'):<11}"
                f"{state}"
            )
    for e in data["excursions"]:
        start = (e["detect_ts"] or t0) - t0
        if e["clear_ts"] is not None:
            span = (f"+{start:.1f}s .. +{e['clear_ts'] - t0:.1f}s "
                    f"({e['straggler_s']:.1f}s, epochs "
                    f"{e['detect_epoch']}..{e['clear_epoch']})")
        else:
            span = f"+{start:.1f}s .. STILL STRAGGLING"
        lines.append(
            f"  straggler: worker {e['worker']}  {span}  "
            f"skew {e.get('skew', 0.0):.2f}  dominant phase "
            f"{e.get('phase', '?')}")
    if not data["excursions"] and data["ranks"]:
        lines.append("  no straggler excursions")
    # elastic fleet: standby promotions render beside the straggler
    # excursions — rank, epoch, takeover latency, and why
    if data.get("standbys"):
        lines.append(f"  standbys registered: "
                     f"{', '.join(data['standbys'])}")
    for p in data.get("promotions") or []:
        when = ""
        if p.get("promote_ts") is not None:
            when = f"+{p['promote_ts'] - t0:.1f}s  "
        lat = ("takeover pending" if p.get("latency_s") is None
               else f"takeover {p['latency_s']:.2f}s")
        lines.append(
            f"  promotion: rank {p['worker']} <- {p['standby_id']}  "
            f"{when}@epoch {p.get('epoch')}  {lat}  ({p.get('why')})")
    for r in data.get("resplits") or []:
        lines.append(
            f"  resplit: generation {r['split_generation']} over ranks "
            f"{r['ranks']} ({r['n_files']} file(s); {r.get('why')})")
    if data["comm"]:
        lines.append("  collective      calls     bytes")
        for k, v in data["comm"].items():
            lines.append(
                f"  {k:<15} {v['calls']:<9} {_fmt_bytes(v['bytes'])}")
    return lines


def cmd_fleet(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    data = _fleet_data(events)
    if args.as_json:
        print(json.dumps(data, indent=2, default=str))
        return 0 if data else 1
    if not data:
        print("no fleet events — the coordinator journals fleet_skew / "
              "straggler_detect once workers attach phase summaries "
              "(obs enabled on a multi-worker run)")
        return 1
    t0 = events[0].get("ts", 0.0)
    n = len(data["ranks"])
    print(f"fleet skew — {n} rank(s), {data['epochs']} fleet epoch(s)"
          + (f", max skew {data['max_skew']:.2f}"
             if data.get("max_skew") is not None else ""))
    for line in _render_fleet(data, t0):
        print(line)
    return 0


# ---- data distribution (train baseline vs live serve) ----

def _data_data(events: list[dict]) -> dict:
    """Aggregate the data leg's journal: per-worker train sketches
    (``data_stats`` plane=train), per-model live windowed sketches
    (``data_stats`` plane=serve), drift excursions, and any
    ``config_stats_missing`` records — entirely from journal files."""
    train: dict = {}
    serve: dict = {}
    excursions: list[dict] = []
    open_: dict = {}
    stats_missing: list[dict] = []
    for ev in events:
        kind = ev.get("event")
        if kind == "data_stats":
            stats = ev.get("stats")
            if not isinstance(stats, dict):
                continue
            if ev.get("plane") == "train":
                train[ev.get("worker")] = {
                    "stats": stats, "epoch": ev.get("epoch"),
                    "ts": ev.get("ts"),
                }
            else:
                serve[ev.get("model") or "default"] = {
                    "stats": stats, "ts": ev.get("ts"),
                    "drift_score": ev.get("drift_score"),
                    "drifting": ev.get("drifting"),
                }
        elif kind == "data_drift":
            key = (ev.get("model"), ev.get("feature"))
            exc = {
                "model": ev.get("model"), "feature": ev.get("feature"),
                "column": ev.get("column"), "stat": ev.get("stat"),
                "score": ev.get("score"), "detect_ts": ev.get("ts"),
                "clear_ts": None, "drift_s": None,
            }
            open_[key] = exc
            excursions.append(exc)
        elif kind == "data_drift_clear":
            exc = open_.pop((ev.get("model"), ev.get("feature")), None)
            if exc is not None:
                exc["clear_ts"] = ev.get("ts")
                exc["drift_s"] = ev.get("drift_s")
        elif kind == "config_stats_missing":
            stats_missing.append({
                "columns": ev.get("columns"),
                "missing": ev.get("missing"),
                "selected": ev.get("selected"),
            })
    if not (train or serve or excursions):
        return {}
    return {"train": train, "serve": serve, "excursions": excursions,
            "config_stats_missing": stats_missing}


def _merged_train_stats(train: dict) -> dict | None:
    """One train baseline out of the per-worker journal snapshots —
    count-weighted merge when numpy is importable (obs/datastats.py),
    else the biggest worker's snapshot (this CLI stays usable on a
    box with nothing but the stdlib)."""
    snaps = [v["stats"] for v in train.values() if v.get("stats")]
    if not snaps:
        return None
    if len(snaps) == 1:
        return snaps[0]
    try:
        from shifu_tensorflow_tpu.obs.datastats import merge_snapshots

        return merge_snapshots(snaps)
    except Exception:
        return max(snaps, key=lambda s: s.get("rows", 0))


def _bundle_baselines(paths: list[str]) -> dict[str, dict]:
    """feature_stats.json baselines out of export dirs: each ``--bundle``
    is either one bundle (name "default") or a multi-tenant models dir
    (one baseline per tenant subdirectory)."""
    import os

    out: dict[str, dict] = {}

    def load(path):
        try:
            with open(path) as f:
                doc = json.load(f)
            return doc.get("stats") or None
        except (OSError, ValueError):
            return None

    for p in paths:
        single = os.path.join(p, "feature_stats.json")
        if os.path.isfile(single):
            stats = load(single)
            if stats:
                out["default"] = stats
            continue
        try:
            names = sorted(os.listdir(p))
        except OSError:
            continue
        for name in names:
            sub = os.path.join(p, name, "feature_stats.json")
            if os.path.isfile(sub):
                stats = load(sub)
                if stats:
                    out[name] = stats
    return out


def _fmt_stat(snap: dict, j: int) -> str:
    mean = snap["mean"][j]
    std = snap["std"][j]
    if mean is None:
        return "-"
    return f"{mean:.4g}±{0.0 if std is None else std:.3g}"


def _pct(snap: dict, key: str, j: int) -> str:
    rates = snap.get(key) or []
    v = rates[j] if j < len(rates) else None
    return "-" if v is None else f"{100.0 * v:.3g}%"


def _render_data(data: dict, baselines: dict, t0: float,
                 max_features: int = 20) -> list[str]:
    lines: list[str] = []
    train_stats = _merged_train_stats(data.get("train") or {})
    open_excs = {(e["model"], e["feature"])
                 for e in data.get("excursions", [])
                 if e["clear_ts"] is None}
    models = sorted(data.get("serve") or {})
    for model in models:
        live_doc = data["serve"][model]
        live = live_doc["stats"]
        base = baselines.get(model)
        base_src = "bundle"
        if base is None and len(baselines) == 1 and len(models) == 1:
            base = next(iter(baselines.values()))
        if base is None:
            base, base_src = train_stats, "journal"
        score = live_doc.get("drift_score")
        lines.append(
            f"  model {model}: live window {live['rows']} rows"
            + (f", baseline {base['rows']} rows [{base_src}]"
               if base else ", NO BASELINE")
            + (f", drift score {score:.3g}" if score is not None else "")
            + (f", {live_doc['drifting']} drifting"
               if live_doc.get("drifting") else "")
        )
        if base is None or base.get("num_features") != live.get(
                "num_features"):
            continue
        rows = []
        try:
            from shifu_tensorflow_tpu.obs.datastats import drift_components
        except Exception:
            drift_components = None
        for j in range(live["num_features"]):
            score_j, stat_j = None, "-"
            if drift_components is not None:
                comps = drift_components(base, live, j)
                stat_j, score_j = max(comps.items(), key=lambda kv: kv[1])
            rows.append((j, score_j, stat_j))
        rows.sort(key=lambda r: -(r[1] or 0.0))
        shown = rows if not max_features else rows[:max_features]
        lines.append(
            "    feat  base mean±std     live mean±std     base p50"
            "   live p50   miss%      score   stat          state")
        bq = (base.get("quantiles") or {}).get("0.5") or []
        lq = (live.get("quantiles") or {}).get("0.5") or []
        for j, score_j, stat_j in shown:
            bp50 = bq[j] if j < len(bq) and bq[j] is not None else None
            lp50 = lq[j] if j < len(lq) and lq[j] is not None else None
            state = ("DRIFTING" if (model, j) in open_excs else "ok")
            lines.append(
                f"    {j:<5} {_fmt_stat(base, j):<17} "
                f"{_fmt_stat(live, j):<17} "
                f"{'-' if bp50 is None else f'{bp50:.4g}':<10} "
                f"{'-' if lp50 is None else f'{lp50:.4g}':<10} "
                f"{_pct(base, 'missing_rate', j)}/"
                f"{_pct(live, 'missing_rate', j):<7} "
                f"{'-' if score_j is None else f'{score_j:.3g}':<7} "
                f"{stat_j:<13} {state}"
            )
        if len(shown) < len(rows):
            lines.append(f"    ... {len(rows) - len(shown)} more features "
                         f"(--features 0 for all)")
    if not models and train_stats:
        lines.append(
            f"  train baseline only: {train_stats['rows']} rows, "
            f"{train_stats['num_features']} features (no serve-plane "
            "data_stats journaled)")
    for e in data.get("excursions", []):
        start = (e["detect_ts"] or t0) - t0
        where = f"model {e['model']} feature {e['feature']}"
        if e.get("column") is not None:
            where += f" (column {e['column']})"
        if e["clear_ts"] is not None:
            span = (f"+{start:.1f}s .. +{e['clear_ts'] - t0:.1f}s "
                    f"({(e['drift_s'] or 0.0):.1f}s)")
        else:
            span = f"+{start:.1f}s .. STILL DRIFTING"
        lines.append(f"  drift: {where}  {span}  stat {e['stat']}  "
                     f"score {e['score']:.3g}")
    if models and not data.get("excursions"):
        lines.append("  no drift excursions")
    for m in data.get("config_stats_missing", []):
        lines.append(
            f"  config: {m['missing']}/{m['selected']} selected columns "
            f"had no columnStats (ZSCALE substituted mean=0/std=1): "
            f"{m['columns']}")
    return lines


def cmd_data(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    data = _data_data(events)
    baselines = _bundle_baselines(args.bundles)
    if args.as_json:
        doc = dict(data) if data else {}
        doc["baselines"] = baselines
        doc["train_merged"] = _merged_train_stats(
            (data or {}).get("train") or {})
        print(json.dumps(doc, indent=2, default=str))
        return 0 if (data or baselines) else 1
    if not data and not baselines:
        print("no data-plane events — the train sketch journals "
              "data_stats per epoch and the serve drift monitor per "
              "window once obs is enabled (shifu.tpu.obs-*)")
        return 1
    t0 = events[0].get("ts", 0.0)
    n_models = len((data or {}).get("serve") or {})
    print(f"data distribution — {n_models} serving model(s), "
          f"{len((data or {}).get('train') or {})} train worker sketch(es), "
          f"{len(baselines)} bundle baseline(s)")
    for line in _render_data(data or {}, baselines, t0,
                             max_features=args.features):
        print(line)
    return 0


# ---- compile flight recorder (data + renderer) ----

def _compile_data(events: list[dict]) -> dict:
    """Aggregate `compile` + `recompile_storm[_clear]` events into the
    per-callable cost table and the storm excursion list — entirely from
    journal files (a dead fleet's included)."""
    per: dict = defaultdict(lambda: {
        "compiles": 0, "compile_s": 0.0, "max_s": 0.0, "wall_s": 0.0,
        "signatures": set(), "warm": 0, "aot_loads": 0,
        "aot_fallbacks": 0, "workers": set(),
        "flops_max": None, "code_bytes": 0,
    })
    storms: list[dict] = []
    open_storms: dict = {}  # (plane, worker) -> storm record
    for ev in events:
        kind = ev.get("event")
        if kind == "compile":
            a = per[ev.get("name", "?")]
            a["signatures"].add(ev.get("signature", "?"))
            if ev.get("worker") is not None:
                a["workers"].add(ev["worker"])
            if ev.get("kind") == "aot_load":
                # a deserialized shipped executable — admission did a
                # LOAD, not a compile; counted in its own column so the
                # table says what admission actually did
                a["aot_loads"] += 1
                a["wall_s"] += float(ev.get("wall_s", 0.0) or 0.0)
                continue
            a["compiles"] += 1
            s = float(ev.get("compile_s", 0.0) or 0.0)
            a["compile_s"] += s
            a["max_s"] = max(a["max_s"], s)
            a["wall_s"] += float(ev.get("wall_s", 0.0) or 0.0)
            if ev.get("kind") == "warm":
                a["warm"] += 1
            elif ev.get("kind") == "aot_fallback":
                a["aot_fallbacks"] += 1
            if ev.get("flops") is not None:
                a["flops_max"] = max(a["flops_max"] or 0.0,
                                     float(ev["flops"]))
            if ev.get("code_bytes"):
                a["code_bytes"] = max(a["code_bytes"],
                                      int(ev["code_bytes"]))
        elif kind == "recompile_storm":
            rec = {
                "started_ts": ev.get("ts"),
                "cleared_ts": None,
                "storm_s": None,
                "culprit": ev.get("culprit"),
                "signature": ev.get("signature"),
                "compiles_in_window": ev.get("compiles_in_window"),
                "plane": ev.get("plane"),
                "worker": ev.get("worker"),
            }
            storms.append(rec)
            open_storms[(ev.get("plane"), ev.get("worker"))] = rec
        elif kind == "recompile_storm_clear":
            rec = open_storms.pop((ev.get("plane"), ev.get("worker")),
                                  None)
            if rec is not None:
                rec["cleared_ts"] = ev.get("ts")
                rec["storm_s"] = ev.get("storm_s")
    callables = {
        name: {
            "compiles": a["compiles"],
            "warm": a["warm"],
            "aot_loads": a["aot_loads"],
            "aot_fallbacks": a["aot_fallbacks"],
            "signatures": len(a["signatures"]),
            "compile_s": round(a["compile_s"], 4),
            "max_s": round(a["max_s"], 4),
            "workers": sorted(a["workers"]),
            **({"flops_max": a["flops_max"]}
               if a["flops_max"] is not None else {}),
            **({"code_bytes": a["code_bytes"]}
               if a["code_bytes"] else {}),
        }
        for name, a in sorted(per.items())
    }
    return {"callables": callables, "storms": storms}


def cmd_compile(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    data = _compile_data(events)
    if args.as_json:
        print(json.dumps(data, indent=2, default=str))
        return 0
    t0 = events[0].get("ts", 0.0)
    if not data["callables"]:
        print("no compile events — was the run traced with obs enabled "
              "on a jax build that reports compile durations?")
        return 1
    total_s = sum(a["compile_s"] for a in data["callables"].values())
    total_n = sum(a["compiles"] for a in data["callables"].values())
    total_aot = sum(a["aot_loads"] for a in data["callables"].values())
    aot_note = (f", {total_aot} AOT executable load(s)"
                if total_aot else "")
    print(f"compile flight recorder — {total_n} compilation(s), "
          f"{total_s:.2f}s total compile time{aot_note}")
    print("  callable                 compiles  warm  aot   fb    "
          "signatures  compile_s  max_s")
    for name, a in data["callables"].items():
        print(f"  {name:<24} {a['compiles']:<9} {a['warm']:<5} "
              f"{a['aot_loads']:<5} {a['aot_fallbacks']:<5} "
              f"{a['signatures']:<11} {a['compile_s']:<10.3f} "
              f"{a['max_s']:.3f}")
    if data["storms"]:
        print()
        print("recompile storms")
        for s in data["storms"]:
            start = (s["started_ts"] or t0) - t0
            if s["cleared_ts"] is not None:
                span = (f"+{start:.1f}s .. +{s['cleared_ts'] - t0:.1f}s "
                        f"({s['storm_s']:.1f}s)")
            else:
                span = f"+{start:.1f}s .. STILL ACTIVE"
            print(f"  {span}  worker {s['worker']}  "
                  f"{s['compiles_in_window']} compiles/window")
            print(f"    churning: {s['culprit']}  last signature "
                  f"{s['signature']}")
    else:
        print("\n  no recompile storms")
    return 0


# ---- device memory (data + renderer) ----

def _mem_data(events: list[dict]) -> dict:
    """Latest + high-water device-memory state per (plane, worker) from
    `device_mem` events, plus the per-model last-known device bytes."""
    per: dict = {}
    models: dict = {}
    for ev in events:
        if ev.get("event") == "model_evict":
            # the eviction's post-release snapshot omits the tenant; a
            # merge-only table would show its bytes forever — exactly
            # inverting the leak diagnosis the snapshot exists for.  A
            # re-admission's device_mem re-adds it below.
            models.pop(ev.get("model"), None)
            continue
        if ev.get("event") != "device_mem":
            continue
        key = f"{ev.get('plane', '?')}/w{ev.get('worker')}" \
            if ev.get("worker") is not None else ev.get("plane", "?")
        a = per.setdefault(key, {"snapshots": 0, "hwm_bytes": 0,
                                 "hwm_ts": None, "last": None})
        a["snapshots"] += 1
        total = int(ev.get("total_bytes", 0) or 0)
        if total >= a["hwm_bytes"]:
            a["hwm_bytes"] = total
            a["hwm_ts"] = ev.get("ts")
        a["last"] = {
            k: ev.get(k) for k in (
                "ts", "total_bytes", "params_bytes", "opt_bytes",
                "infeed_bytes", "exec_bytes", "other_bytes", "arrays",
                "bytes_in_use", "bytes_limit", "devmem_frac", "epoch")
            if ev.get(k) is not None
        }
        for m, b in (ev.get("models") or {}).items():
            models[m] = int(b)
    return {"workers": per, "models": models}


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024.0
    return f"{n:.1f}GiB"


def cmd_mem(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    data = _mem_data(events)
    if args.as_json:
        print(json.dumps(data, indent=2, default=str))
        return 0
    if not data["workers"]:
        print("no device_mem events — the device-memory accountant "
              "snapshots per train epoch and per serve admission; was "
              "obs enabled?")
        return 1
    print("device memory accountant")
    print("  writer          snaps  high-water  last-total  params    "
          "opt       infeed    other")
    for key, a in sorted(data["workers"].items()):
        last = a["last"] or {}
        print(
            f"  {key:<15} {a['snapshots']:<6} "
            f"{_fmt_bytes(a['hwm_bytes']):<11} "
            f"{_fmt_bytes(last.get('total_bytes')):<11} "
            f"{_fmt_bytes(last.get('params_bytes')):<9} "
            f"{_fmt_bytes(last.get('opt_bytes')):<9} "
            f"{_fmt_bytes(last.get('infeed_bytes')):<9} "
            f"{_fmt_bytes(last.get('other_bytes'))}"
        )
        if last.get("bytes_limit"):
            print(f"                  backend: "
                  f"{_fmt_bytes(last.get('bytes_in_use'))} in use of "
                  f"{_fmt_bytes(last['bytes_limit'])} limit "
                  f"({100.0 * (last.get('devmem_frac') or 0):.1f}%)")
    if data["models"]:
        print("  model           device-bytes")
        for m, b in sorted(data["models"].items()):
            print(f"  {m:<15} {_fmt_bytes(b)}")
    return 0


# ---- profile captures ----

def cmd_profile(args) -> int:
    if args.request:
        from shifu_tensorflow_tpu.obs import profile as obs_profile

        if not args.out_dir:
            print("--request needs --dir (where the profiler dump "
                  "should land)", file=sys.stderr)
            return 2
        path = obs_profile.request(args.journal, args.out_dir,
                                   seconds=args.seconds,
                                   worker=args.worker)
        print(f"capture requested: trigger {path} "
              f"({args.seconds:.1f}s window -> {args.out_dir}); the "
              "fleet's next obs tick starts it")
        return 0
    events = read_events(args.journal)
    caps = [e for e in events if e.get("event") == "profile_capture"]
    if args.as_json:
        for ev in caps:
            print(json.dumps(ev, separators=(",", ":"), default=str))
        return 0 if caps else 1
    if not caps:
        print(f"no profile_capture events under {args.journal!r}; "
              "request one with: obs profile --journal ... --request "
              "--dir <dump-dir>", file=sys.stderr)
        return 1
    t0 = events[0].get("ts", 0.0)
    print(f"profiler captures ({len(caps)} event(s))")
    for ev in caps:
        print(" " + _fmt_event(ev, t0))
    return 0


# ---- top ----

def _scrape(url: str, timeout: float = 2.0) -> dict[str, float]:
    """One /metrics scrape → {metric_name: value} (labels stripped; the
    last sample of a name wins).  Any failure returns {} — top renders
    from the journal alone."""
    import urllib.request

    try:
        text = urllib.request.urlopen(url, timeout=timeout).read().decode()
    except Exception:
        return {}
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            name = key.split("{", 1)[0]
            out[name] = float(val)
        except ValueError:
            continue
    return out


def _render_top(base: str, urls: list[str],
                cache: dict | None = None) -> list[str] | None:
    data = _build_summary(base, cache=cache)
    if data is None:
        return None
    events = data.pop("_events")
    now = time.time()
    scraped: dict[str, float] = {}
    reachable = 0
    for url in urls:
        got = _scrape(url)
        if got:
            reachable += 1
            scraped.update(got)
    lines = []
    age = now - data["t1"]
    lines.append(
        f"obs top — {base}  |  {data['events']} events, last {age:.1f}s ago"
        + (f"  |  job {', '.join(data['jobs'])}" if data["jobs"] else "")
        + (f"  |  scraped {reachable}/{len(urls)} targets" if urls else "")
    )
    lines.append("")
    # slo: journaled transitions + live gauges when a scrape answered
    slo = data["slo"]
    gauge_signals = sorted({
        m[len("stpu_slo_"):].removesuffix("_breached").removesuffix(
            "_target").removesuffix("_z")
        for m in scraped if m.startswith("stpu_slo_")
    })
    if slo or gauge_signals:
        lines.append("slo")
        names = sorted(set(slo) | set(gauge_signals))
        lines.append("  signal            state      value      target")
        for name in names:
            s = slo.get(name, {})
            value = scraped.get(f"stpu_slo_{name}", s.get("last_value"))
            target = scraped.get(f"stpu_slo_{name}_target", s.get("target"))
            live = scraped.get(f"stpu_slo_{name}_breached")
            breached = bool(live) if live is not None \
                else s.get("breached", False)
            lines.append(
                f"  {name:<17} {'BREACHED' if breached else 'ok':<10} "
                f"{'?' if value is None else f'{value:.4g}':<10} "
                f"{'-' if not target else f'{target:.4g}'}"
            )
        lines.append("")
    # train budget
    budget = data["budget"]
    if budget:
        lines.append("train")
        lines.append("  worker  epochs  steps  step_ms   infeed%  other%")
        for w, a in budget.items():
            lines.append(
                f"  {w:<7} {a['epochs']:<7} {a['steps']:<6} "
                f"{a['step_ms']:<9.3f} {a['pct']['infeed']:<8.1f} "
                f"{a['pct']['other']:.1f}"
            )
        lines.append("")
    # fleet panel: per-rank skew + straggler state beside the serve
    # panel (journal-fed; the live stpu_fleet_* gauges ride the
    # coordinator metrics op, which top's --metrics-url can scrape)
    fleet = data.get("fleet") or {}
    if fleet.get("ranks") or fleet.get("excursions"):
        lines.append("fleet")
        for line in _render_fleet(fleet, data["t0"]):
            lines.append(line)
        lines.append("")
    # data panel: per-model drift state from the journaled windowed
    # sketches (live stpu_data_* gauges ride the same /metrics scrape
    # as everything else when --metrics-url is given)
    data_leg = data.get("data") or {}
    if data_leg:
        lines.append("data")
        for line in _render_data_brief(data_leg):
            lines.append(line)
        lines.append("")
    # serve plane: journal rows, live counters when scraped
    serve = data["serve"]
    if serve and (serve["workers"] or serve["fleet"]["workers"]):
        lines.append("serve")
        for line in _render_serve(serve):
            lines.append(line)
        live_reqs = scraped.get("stpu_serve_requests_total")
        if live_reqs is not None:
            lines.append(
                f"  live: requests {int(live_reqs)}, shed "
                f"{int(scraped.get('stpu_serve_shed_total', 0))}, queue "
                f"{int(scraped.get('stpu_serve_queue_rows', 0))} rows "
                f"(one scraped worker's view)"
            )
        lines.append("")
    lines.append("recent events")
    t0 = data["t0"]
    timeline = [e for e in events if e.get("event") not in _BULK_EVENTS]
    for ev in timeline[-8:]:
        lines.append(" " + _fmt_event(ev, t0))
    return lines


# ---- bulk scoring job reconstruction ----

SCORE_SCHEMA = "stpu.obs.score/1"


def _score_data(events: list[dict]) -> dict:
    """One score job's story out of the journal: the driver emits
    ``score_job_start``/``score_job_finished`` and the lease table
    emits every ``lease_*`` / ``shard_commit`` / duplicate transition —
    enough to reconstruct shard ownership history, per-worker commit
    counts, and the exactly-once audit (committed vs duplicate tokens)
    from a dead fleet's files alone."""
    jobs: dict = {}
    # the lease table emits its events without a job field (it predates
    # nothing — it simply doesn't know the id); attribute them to the
    # most recently STARTED job, which is correct because one driver
    # runs one job at a time and events are merged time-ordered
    current: list = [None]

    def job(ev) -> dict:
        key = ev.get("job") or current[0] or "?"
        return jobs.setdefault(key, {
            "job": key, "start_ts": None, "finish_ts": None,
            "shards": None, "noop": False, "rows": None,
            "committed": {}, "duplicates": [], "grants": 0,
            "expiries": [], "reclaims": [], "workers": {},
            "timeline": [],
        })

    for ev in events:
        kind = ev.get("event")
        if kind == "score_job_start":
            current[0] = ev.get("job") or current[0]
            j = job(ev)
            j["start_ts"] = ev.get("ts")
            j["shards"] = ev.get("shards")
            j["noop"] = bool(ev.get("noop"))
            j["timeline"].append(ev)
        elif kind == "score_job_finished":
            j = job(ev)
            j["finish_ts"] = ev.get("ts")
            j["rows"] = ev.get("rows")
            j["noop"] = bool(ev.get("noop")) or j["noop"]
            j["timeline"].append(ev)
        elif kind in ("lease_grant", "lease_expire", "lease_reclaim",
                      "shard_commit", "shard_discarded_duplicate"):
            j = job(ev)
            j["timeline"].append(ev)
            if kind == "lease_grant":
                j["grants"] += 1
            elif kind == "lease_expire":
                j["expiries"].append(ev)
            elif kind == "lease_reclaim":
                j["reclaims"].append(ev)
            elif kind == "shard_commit":
                j["committed"][ev.get("shard")] = ev
                w = ev.get("worker") or "?"
                j["workers"][w] = j["workers"].get(w, 0) + 1
            else:
                j["duplicates"].append(ev)
    out = [j for j in jobs.values() if j["timeline"]]
    if not out:
        return {}
    for j in out:
        j["committed_rows"] = sum(
            int(e.get("rows") or 0) for e in j["committed"].values())
        tokens = [e.get("lease") for e in j["committed"].values()]
        j["duplicate_committed_tokens"] = len(tokens) - len(set(tokens))
    return {"schema": SCORE_SCHEMA, "jobs": out}


def _render_score(data: dict, t0: float) -> list[str]:
    lines: list[str] = []
    for j in data["jobs"]:
        n_committed = len(j["committed"])
        total = j["shards"] if j["shards"] is not None else "?"
        state = ("no-op (already sealed)" if j["noop"]
                 else "finished" if j["finish_ts"] is not None
                 else "RUNNING/DEAD")
        lines.append(f"score job {j['job']} — {state}: "
                     f"{n_committed}/{total} shard(s) committed, "
                     f"{j['committed_rows']} row(s)")
        lines.append(f"  grants {j['grants']}  expiries "
                     f"{len(j['expiries'])}  reclaims "
                     f"{len(j['reclaims'])}  duplicates discarded "
                     f"{len(j['duplicates'])}  duplicate committed "
                     f"tokens {j['duplicate_committed_tokens']}")
        if j["workers"]:
            per = "  ".join(f"{w}={n}" for w, n in
                            sorted(j["workers"].items()))
            lines.append(f"  commits by worker: {per}")
        for ev in j["timeline"]:
            lines.append(" " + _fmt_event(ev, t0))
    return lines


def cmd_score(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    data = _score_data(events)
    if args.as_json:
        print(json.dumps(data, indent=2, default=str))
        return 0 if data else 1
    if not data:
        print("no score-plane events — run the job with "
              "`python -m shifu_tensorflow_tpu.score run --journal ...`")
        return 1
    t0 = events[0].get("ts", 0.0)
    for line in _render_score(data, t0):
        print(line)
    return 0


# ---- lifecycle reconstruction ----

LIFECYCLE_SCHEMA = "stpu.obs.lifecycle/1"

#: controller-plane events that open/advance/close a lifecycle cycle
_CYCLE_EVENTS = (
    "lifecycle_trigger", "retrain_start", "retrain_done", "shadow_admit",
    "ramp_step", "promote", "rollback",
)


def _lifecycle_data(events: list[dict],
                    model: str | None = None) -> dict:
    """Lifecycle cycles out of the journal: the controller's ``.l``
    writer emits every transition with its evidence, the serve workers
    echo ``lifecycle_ctl_applied`` / ``weight_change`` as they converge
    on the ctl intent — together enough to reconstruct each cycle
    (trigger → retrain → shadow → ramp → verdict), its wall-clock
    latency, and whether the fleet actually actuated each step, from a
    dead fleet's files alone."""
    cycles: list = []
    open_by_model: dict = {}

    def cycle_for(ev, *, open_new: bool) -> dict | None:
        m = ev.get("model") or "?"
        c = open_by_model.get(m)
        if c is None and open_new:
            c = {
                "model": m, "trigger_ts": None, "verdict": None,
                "verdict_ts": None, "generation": None,
                "evidence": None, "retrain": None, "ramp_steps": [],
                "ctl_applied": [], "weight_changes": [],
                "timeline": [],
            }
            open_by_model[m] = c
            cycles.append(c)
        return c

    for ev in events:
        kind = ev.get("event")
        m = ev.get("model")
        if model is not None and m is not None and m != model \
                and not str(m).startswith(f"{model}."):
            continue
        if kind == "lifecycle_trigger":
            # a trigger while a cycle is open means the previous
            # controller died verdict-less: close it as such
            stale = open_by_model.pop(m or "?", None)
            if stale is not None and stale["verdict"] is None:
                stale["verdict"] = "abandoned"
            c = cycle_for(ev, open_new=True)
            c["trigger_ts"] = ev.get("ts")
            c["evidence"] = ev.get("evidence") or ev.get("signals")
            c["timeline"].append(ev)
        elif kind in _CYCLE_EVENTS:
            c = cycle_for(ev, open_new=True)
            c["timeline"].append(ev)
            if kind == "retrain_start":
                c["generation"] = ev.get("generation", c["generation"])
            elif kind == "retrain_done":
                c["retrain"] = {
                    "ok": bool(ev.get("ok")), "rc": ev.get("rc"),
                    "why": ev.get("why"),
                    "duration_s": ev.get("duration_s"),
                }
            elif kind == "ramp_step":
                c["ramp_steps"].append(ev.get("fraction"))
            elif kind in ("promote", "rollback"):
                c["verdict"] = kind
                c["verdict_ts"] = ev.get("ts")
                if kind == "rollback":
                    c["rollback_reason"] = ev.get("reason")
                open_by_model.pop(c["model"], None)
        elif kind == "lifecycle_ctl_applied":
            for c in cycles:
                if c["verdict"] is None:
                    c["ctl_applied"].append(ev)
                    c["timeline"].append(ev)
        elif kind == "weight_change":
            for c in cycles:
                if c["verdict"] is None:
                    c["weight_changes"].append(ev)
                    c["timeline"].append(ev)
    if model is not None:
        cycles = [c for c in cycles if c["model"] == model]
    if not cycles:
        return {}
    for c in cycles:
        if c["trigger_ts"] is not None and c["verdict_ts"] is not None:
            c["latency_s"] = round(c["verdict_ts"] - c["trigger_ts"], 3)
        else:
            c["latency_s"] = None
        if c["verdict"] is None:
            c["verdict"] = "in-flight"
    return {"schema": LIFECYCLE_SCHEMA, "cycles": cycles}


def _render_lifecycle(data: dict, t0: float) -> list[str]:
    lines: list[str] = []
    for i, c in enumerate(data["cycles"]):
        gen = (f" gen {c['generation']}"
               if c["generation"] is not None else "")
        lat = (f" in {c['latency_s']}s"
               if c["latency_s"] is not None else "")
        lines.append(f"cycle {i} — model {c['model']}{gen}: "
                     f"{c['verdict'].upper()}{lat}")
        if c.get("evidence"):
            lines.append(f"  trigger evidence: {_short(c['evidence'])}")
        r = c.get("retrain")
        if r:
            state = "ok" if r["ok"] else f"FAILED ({r.get('why')})"
            dur = (f" in {r['duration_s']:.1f}s"
                   if isinstance(r.get("duration_s"), (int, float))
                   else "")
            lines.append(f"  retrain: {state} rc={r.get('rc')}{dur}")
        if c["ramp_steps"]:
            lines.append("  ramp: " + " -> ".join(
                f"{f:g}" for f in c["ramp_steps"] if f is not None))
        if c.get("rollback_reason"):
            lines.append(f"  rollback reason: {c['rollback_reason']}")
        lines.append(f"  fleet actuation: {len(c['ctl_applied'])} ctl "
                     f"apply(s), {len(c['weight_changes'])} weight "
                     f"change(s)")
        for ev in c["timeline"]:
            lines.append(" " + _fmt_event(ev, t0))
    return lines


def cmd_lifecycle(args) -> int:
    events = read_events(args.journal)
    if not events:
        print(f"no journal events under {args.journal!r} "
              f"(files: {journal_files(args.journal) or 'none'})",
              file=sys.stderr)
        return 1
    data = _lifecycle_data(events, model=args.model)
    if args.as_json:
        print(json.dumps(data, indent=2, default=str))
        return 0 if data else 1
    if not data:
        print("no lifecycle events — run the controller with "
              "`python -m shifu_tensorflow_tpu.lifecycle run ...` "
              "against this journal")
        return 1
    t0 = events[0].get("ts", 0.0)
    for line in _render_lifecycle(data, t0):
        print(line)
    return 0


def cmd_top(args) -> int:
    # per-file parse cache: rotated journal files are immutable, so each
    # refresh re-reads only the growing active files, not the whole
    # rotation set ("tail", not "re-read everything, every 2 seconds")
    cache: dict = {}
    while True:
        frame = _render_top(args.journal, args.metrics_urls, cache)
        if frame is None:
            print(f"no journal events under {args.journal!r} "
                  f"(files: {journal_files(args.journal) or 'none'})",
                  file=sys.stderr)
            return 1
        if args.once:
            print("\n".join(frame))
            return 0
        # ANSI clear + home: a plain terminal dashboard, no curses dep
        sys.stdout.write("\x1b[2J\x1b[H" + "\n".join(frame) + "\n")
        sys.stdout.flush()
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "tail":
            return cmd_tail(args)
        if args.cmd == "trace":
            return cmd_trace(args)
        if args.cmd == "top":
            return cmd_top(args)
        if args.cmd == "fleet":
            return cmd_fleet(args)
        if args.cmd == "report":
            return cmd_report(args)
        if args.cmd == "diff":
            return cmd_diff(args)
        if args.cmd == "data":
            return cmd_data(args)
        if args.cmd == "compile":
            return cmd_compile(args)
        if args.cmd == "mem":
            return cmd_mem(args)
        if args.cmd == "profile":
            return cmd_profile(args)
        if args.cmd == "score":
            return cmd_score(args)
        if args.cmd == "lifecycle":
            return cmd_lifecycle(args)
        return cmd_summary(args)
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        # `... | head` closes our stdout mid-timeline; that is the
        # reader's prerogative, not an error
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
