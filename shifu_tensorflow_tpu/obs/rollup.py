"""Long-horizon rollup archive: the obs plane's time axis.

Every other obs leg is within-run and rotation-bounded: journals cap at
``max_bytes × max_files`` per writer (obs/journal.py), so a multi-day
job silently loses its own history, and nothing compares run N against
run N−1.  This module adds the missing axis in three parts, stdlib-only
and off-path like every other leg:

1. **Rollup compactor** (:class:`RollupCompactor`) — a per-writer tap
   on the journal's emit path that folds events into one downsampled
   aggregate record per window (default 60 s), appended to a
   ``<journal>.rollup.jsonl`` sidecar that is EXEMPT from rotation.
   Each record carries the window's event counts, per-model serve
   volume, per-rank train phase seconds, gauge high-waters, compile
   cost, the SLO watchdog's windowed digest snapshots, DataSketch
   snapshots, excursion intervals (SLO/storm/drift/straggler/
   regression), and — crucially — per-window DELTAS of every registered
   MONOTONIC counter source (serve request/shed counters, the cost
   accountant): rate-limited journal events (``shed``) can undercount,
   counters cannot.  Hours of history cost KBs; a dead fleet's full run
   reconstructs from the sidecars alone (:func:`reconstruct`) after its
   journals rotated away.

   Restart discipline: a compactor never re-reads its sidecar or its
   journal — it only appends windows folded from events it saw and
   counter deltas against baselines that start at the source's birth —
   so a crash mid-window loses at most that window's in-memory fold and
   a restart can never double-count (pinned by test).

2. **Cross-run comparison** — :func:`reconstruct` merges a sidecar set
   into one run document (counters summed, digests count-weight merged,
   gauges maxed, excursions deduped); ``obs report`` renders it and
   ``obs diff`` compares two runs with noise-aware significance: a
   delta only counts when it clears both the relative floor and a
   ``k/√n`` discount on the smaller side's sample count (the same
   small-sample discipline the data-drift scorer uses).

3. **Regression watchdog** (:class:`RegressionWatchdog`) — compares the
   LIVE windowed digests (obs/slo.py) against a pinned baseline rollup
   (``shifu.tpu.obs-baseline``) on the serve SLO tick / train epoch
   tick, and journals hysteretic ``perf_regression`` /
   ``perf_regression_clear`` events naming the metric and magnitude
   when the live/baseline ratio holds past ``shifu.tpu.slo-regression``.

Sidecar lines are plain JSON with a ``schema`` field; readers skip torn
lines exactly like the journal's.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import threading
import time
from typing import Any, Callable

from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs.rollup")

__all__ = [
    "ROLLUP_SUFFIX",
    "ROLLUP_SCHEMA",
    "RollupCompactor",
    "RegressionWatchdog",
    "rollup_path",
    "rollup_files",
    "read_rollups",
    "reconstruct",
    "merge_digest_snapshots",
    "load_baseline",
    "register_source",
    "unregister_source",
    "install",
    "uninstall",
    "active",
    "install_regression",
    "uninstall_regression",
    "regression_active",
    "tick",
]

ROLLUP_SUFFIX = ".rollup.jsonl"
ROLLUP_SCHEMA = "stpu.obs.rollup/1"

#: wall-clock seam (monkeypatchable by the frozen-clock drills)
_time = time.time

#: excursion-opening events → (kind, fn(rec) -> excursion name)
_OPEN_KINDS: dict[str, tuple[str, Callable[[dict], str]]] = {
    "slo_breach": ("slo", lambda r: str(r.get("signal", "?"))),
    "recompile_storm": ("storm", lambda r: str(r.get("culprit", "?"))),
    "data_drift": (
        "drift",
        lambda r: f"{r.get('model', '?')}/f{r.get('feature', '?')}",
    ),
    "straggler_detect": ("straggler",
                         lambda r: f"worker {r.get('worker', '?')}"),
    "perf_regression": ("regression",
                        lambda r: str(r.get("metric", "?"))),
}

#: excursion-closing events → the kind they close
_CLOSE_KINDS: dict[str, tuple[str, Callable[[dict], str]]] = {
    "slo_recover": _OPEN_KINDS["slo_breach"],
    "recompile_storm_clear": _OPEN_KINDS["recompile_storm"],
    "data_drift_clear": _OPEN_KINDS["data_drift"],
    "straggler_clear": _OPEN_KINDS["straggler_detect"],
    "perf_regression_clear": _OPEN_KINDS["perf_regression"],
}

# ---- monotonic-counter sources -----------------------------------------------

#: name -> zero-arg callable returning a flat {key: number} dict of
#: CUMULATIVE counters.  The compactor polls every source at each window
#: flush and records per-window deltas; baselines start at the source's
#: birth (0 for a fresh registry), so the deltas sum back to the exact
#: live totals — the conservation property the rotation drill pins.
#: Process-global on purpose: sources (a serve server's metrics, the
#: cost accountant) register whenever they come up, before or after the
#: compactor installs.
_sources: dict[str, Callable[[], dict]] = {}


def register_source(name: str, fn: Callable[[], dict]) -> None:
    """Register (or replace) a counter source.  Replacement resets the
    delta baseline via the compactor's reset clamp — a counter that
    moves BACKWARD (new registry) is treated as restarted from zero,
    Prometheus ``rate()`` semantics."""
    _sources[name] = fn


def unregister_source(name: str) -> None:
    _sources.pop(name, None)


class _WindowFold:
    """One in-progress rollup window's accumulation state."""

    __slots__ = ("t0", "events", "serve", "train", "gauges", "compile",
                 "data", "excursions")

    def __init__(self, t0: float):
        self.t0 = t0
        self.events: dict[str, int] = {}
        self.serve: dict[str, dict] = {}
        self.train: dict[str, dict] = {}
        self.gauges: dict[str, float] = {}
        self.compile: dict[str, float] = {}
        self.data: dict[str, dict] = {}
        self.excursions: list[dict] = []


class RollupCompactor:
    """Fold journal events + counter deltas into per-window sidecar
    records.  ``note_event`` is the journal tap (one dict fold, no IO
    unless the window rolled); ``flush`` writes one JSON line; a daemon
    thread flushes idle windows so counter deltas keep flowing even
    when no events do."""

    def __init__(self, path: str, *, window_s: float = 60.0,
                 plane: str | None = None, worker: int | None = None,
                 job: str | None = None, thread: bool = True):
        self.path = os.fspath(path)
        self.window_s = max(1.0, float(window_s))
        self.plane = plane
        self.worker = worker
        self.job = job
        self._lock = threading.Lock()
        self._cur: _WindowFold | None = None
        # (source, key) -> last absolute value polled (delta baseline).
        # Starts EMPTY: the first poll's delta is the full counter value,
        # so counts between source birth and first flush are never lost.
        self._last: dict[tuple[str, str], float] = {}
        # signal -> (count, sum) at the previous digest snapshot: the
        # SLO digests are sliding windows that OVERLAP successive
        # rollup windows, so recording raw counts would inflate them —
        # each record instead carries new_count/new_sum (the growth
        # since the last flush), which reconstruct sums back to the
        # exact observation total (conservation, like the counters)
        self._digest_last: dict[str, tuple[int, float]] = {}
        self._open_exc: dict[tuple[str, str], dict] = {}
        self._file: int | None = None
        self._warned = False
        self._closed = False
        # wall time of the last flush: the daemon loop is a FALLBACK
        # for idle/eventless windows — when the event-driven boundary
        # roll already flushed this window, the daemon defers, so
        # steady traffic yields ONE record per window, not two
        self._flushed_at = _time()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if thread:
            self._thread = threading.Thread(
                target=self._flush_loop, name="obs-rollup", daemon=True)
            self._thread.start()
            # a short-lived worker (one fast fit) can exit BEFORE the
            # first periodic tick and before anything closes its
            # journal — the atexit flush is what makes its final
            # windows (and final counter deltas) land; close()
            # unregisters, and a SIGKILL still loses at most one window
            import atexit

            atexit.register(self.close)

    # ---- folding (journal tap) ----
    def note_event(self, rec: dict) -> None:
        ev = rec.get("event")
        if not isinstance(ev, str):
            return
        ts = float(rec.get("ts") or _time())
        try:
            with self._lock:
                if self._closed:
                    return
                self._roll_locked(ts)
                self._fold_locked(ev, rec, ts)
        except Exception:
            # the compactor must never take down the journal write that
            # fed it — same contract as the journal itself
            if not self._warned:
                self._warned = True
                log.warning("rollup fold failed; further fold errors "
                            "are silent", exc_info=True)

    def _roll_locked(self, ts: float) -> None:
        start = (ts // self.window_s) * self.window_s
        if self._cur is None:
            self._cur = _WindowFold(start)
        elif start > self._cur.t0:
            self._flush_locked(self._cur.t0 + self.window_s)
            self._cur = _WindowFold(start)

    def _fold_locked(self, ev: str, rec: dict, ts: float) -> None:
        w = self._cur
        w.events[ev] = w.events.get(ev, 0) + 1
        if ev == "serve_batch":
            m = w.serve.setdefault(str(rec.get("model") or "default"), {
                "rows": 0, "requests": 0, "batches": 0,
                "padded_rows": 0, "dispatch_s": 0.0, "queue_delay_s": 0.0,
            })
            m["rows"] += int(rec.get("rows", 0) or 0)
            m["requests"] += int(rec.get("requests", 0) or 0)
            m["batches"] += 1
            m["padded_rows"] += int(rec.get("bucket", 0) or 0)
            m["dispatch_s"] += float(rec.get("dispatch_s", 0.0) or 0.0)
            m["queue_delay_s"] += float(
                rec.get("queue_delay_s", 0.0) or 0.0)
        elif ev == "step_breakdown":
            t = w.train.setdefault(str(rec.get("worker", 0) or 0), {
                "epochs": 0, "steps": 0, "dispatch_s": 0.0,
                "infeed_s": 0.0, "host_s": 0.0, "block_s": 0.0,
                "train_time_s": 0.0,
            })
            t["epochs"] += 1
            t["steps"] += int(rec.get("steps", 0) or 0)
            for k in ("dispatch_s", "infeed_s", "host_s", "block_s"):
                t[k] += float(rec.get(k, 0.0) or 0.0)
        elif ev == "epoch":
            # setdefault, not get: the trainer emits `epoch` BEFORE
            # `step_breakdown`, so the window's first epoch event must
            # be able to mint the worker's row or its train_time_s is
            # silently dropped every window
            t = w.train.setdefault(str(rec.get("worker", 0) or 0), {
                "epochs": 0, "steps": 0, "dispatch_s": 0.0,
                "infeed_s": 0.0, "host_s": 0.0, "block_s": 0.0,
                "train_time_s": 0.0,
            })
            t["train_time_s"] += float(
                rec.get("train_time_s", 0.0) or 0.0)
        elif ev == "device_mem":
            for key in ("total_bytes", "devmem_frac"):
                v = rec.get(key)
                if v is not None:
                    w.gauges[key] = max(w.gauges.get(key, 0.0), float(v))
        elif ev == "compile":
            if rec.get("kind") == "aot_load":
                # a deserialized shipped executable (export/aot.py) is
                # an admission LOAD, not a compile — its ~0 compile_s
                # must not dilute the window's compile-cost fold
                w.compile["aot_loads"] = w.compile.get("aot_loads", 0) + 1
                return
            w.compile["compiles"] = w.compile.get("compiles", 0) + 1
            s = float(rec.get("compile_s", 0.0) or 0.0)
            w.compile["compile_s"] = w.compile.get("compile_s", 0.0) + s
            w.compile["max_s"] = max(w.compile.get("max_s", 0.0), s)
            if rec.get("kind") == "aot_fallback":
                w.compile["aot_fallbacks"] = (
                    w.compile.get("aot_fallbacks", 0) + 1)
        elif ev == "data_stats":
            stats = rec.get("stats")
            if isinstance(stats, dict):
                if rec.get("plane") == "train":
                    key = f"train:w{rec.get('worker', 0) or 0}"
                else:
                    key = f"serve:{rec.get('model') or 'default'}"
                # last-wins within the window: train sketches are
                # CUMULATIVE per fit and serve sketches windowed, so
                # summing them would double-count; reconstruct keeps
                # the last across windows for the same reason
                w.data[key] = stats
        if ev in _OPEN_KINDS:
            kind, name_of = _OPEN_KINDS[ev]
            name = name_of(rec)
            self._open_exc[(kind, name)] = {
                "kind": kind, "name": name,
                "start_ts": ts, "end_ts": None,
            }
        elif ev in _CLOSE_KINDS:
            kind, name_of = _CLOSE_KINDS[ev]
            name = name_of(rec)
            exc = self._open_exc.pop((kind, name), None)
            if exc is None:
                # close without a seen open (the open predates this
                # compactor): record the interval with an unknown start
                exc = {"kind": kind, "name": name, "start_ts": None}
            exc["end_ts"] = ts
            w.excursions.append(exc)

    # ---- flushing ----
    def _poll_counters_locked(self) -> dict[str, dict[str, float]]:
        out: dict[str, dict[str, float]] = {}
        for src, fn in list(_sources.items()):
            try:
                cur = fn()
            except Exception:
                continue
            if not isinstance(cur, dict):
                continue
            deltas: dict[str, float] = {}
            for key, val in cur.items():
                try:
                    val = float(val)
                except (TypeError, ValueError):
                    continue
                last = self._last.get((src, key), 0.0)
                if val < last:
                    last = 0.0  # counter reset (replaced source/registry)
                d = val - last
                self._last[(src, key)] = val
                if d:
                    deltas[key] = round(d, 6)
            if deltas:
                out[src] = deltas
        return out

    def _digest_snapshots(self) -> dict[str, dict]:
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        wd = obs_slo.active()
        if wd is None:
            return {}
        try:
            raw = wd.digest_snapshots()
            totals = wd.digest_totals()
        except Exception:
            return {}
        out: dict[str, dict] = {}
        # iterate the TOTALS (a superset of the live snapshots): a
        # signal whose window expired before this flush has no snapshot
        # but its observations still happened — conservation demands
        # their count/sum land in SOME record, values-unknown or not
        for sig, (cur_n, cur_s) in totals.items():
            snap = raw.get(sig)
            if snap is None and cur_n == 0:
                continue
            # delta-ize against the digest's LIFETIME totals (monotonic
            # — the windowed count shrinks as cells expire and cannot
            # be delta-ized)
            prev_n, prev_s = self._digest_last.get(sig, (0, 0.0))
            if cur_n < prev_n:
                prev_n, prev_s = 0, 0.0  # watchdog replaced: reset
            self._digest_last[sig] = (cur_n, cur_s)
            new_n = cur_n - prev_n
            if new_n <= 0:
                continue  # nothing new since the last flush
            rec = ({k: v for k, v in snap.items()
                    if k not in ("total_count", "total_sum")}
                   if snap is not None else {})
            rec["new_count"] = new_n
            rec["new_sum"] = round(cur_s - prev_s, 6)
            out[sig] = rec
        return out

    def flush(self, now: float | None = None) -> None:
        """Flush the current window (plus counter deltas) to the
        sidecar.  Public for tests and the journal-close hook; the
        daemon thread calls it once per window so an idle journal still
        records counter movement."""
        with self._lock:
            if self._closed:
                return
            self._flush_locked(now)
            self._cur = None

    def _flush_locked(self, now: float | None = None) -> None:
        now = _time() if now is None else now
        self._flushed_at = now
        w = self._cur
        counters = self._poll_counters_locked()
        digests = self._digest_snapshots()
        rec: dict[str, Any] = {
            "schema": ROLLUP_SCHEMA,
            "t0": round(w.t0 if w is not None else now, 6),
            "t1": round(now, 6),
        }
        if self.plane is not None:
            rec["plane"] = self.plane
        if self.worker is not None:
            rec["worker"] = self.worker
        if self.job is not None:
            rec["job"] = self.job
        empty = True
        if w is not None and w.events:
            rec["events"] = w.events
            empty = False
        if w is not None:
            for field in ("serve", "train", "gauges", "compile", "data"):
                val = getattr(w, field)
                if val:
                    rec[field] = val
                    empty = False
            if w.excursions:
                rec["excursions"] = w.excursions
                empty = False
        if counters:
            rec["counters"] = counters
            empty = False
        if digests:
            rec["digests"] = digests
            empty = False
        if self._open_exc:
            rec["open_excursions"] = [
                dict(e) for e in self._open_exc.values()]
        if empty:
            return  # an idle window costs zero bytes
        self._write_locked(rec)

    def _write_locked(self, rec: dict) -> None:
        try:
            line = (json.dumps(rec, separators=(",", ":"), default=str)
                    + "\n").encode("utf-8")
        except (TypeError, ValueError):
            return
        try:
            if self._file is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._file = os.open(
                    self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                    0o644)
            os.write(self._file, line)
        except OSError as e:
            if not self._warned:
                self._warned = True
                log.warning("rollup write to %s failed (%s); further "
                            "records will be dropped", self.path, e)

    def _flush_loop(self) -> None:
        period = min(self.window_s, 5.0)
        while not self._stop.wait(period):
            now = _time()
            # defer to the event-driven boundary flush: only step in
            # when a full window has passed with nothing flushing
            if now - self._flushed_at >= self.window_s:
                try:
                    self.flush(now)
                except Exception:
                    pass

    def close(self) -> None:
        """Final flush (captures the partial window + final counter
        deltas) and stop.  Installed as the journal's close hook AND an
        atexit handler, so a SIGTERM-drained fleet's (or a fast-exiting
        worker's) sidecar is complete; a SIGKILL loses at most the
        current window."""
        self._stop.set()
        if self._thread is not None:
            import atexit

            try:
                atexit.unregister(self.close)
            except Exception:
                pass
        with self._lock:
            if self._closed:
                return
            try:
                self._flush_locked()
            except Exception:
                pass
            self._closed = True
            if self._file is not None:
                try:
                    os.close(self._file)
                except OSError:
                    pass
                self._file = None


# ---- reading -----------------------------------------------------------------

def rollup_path(journal_path: str) -> str:
    """The sidecar path for one WRITER's journal path (siblings keep
    their ``.w<k>``/``.s<k>`` suffix: one writer per sidecar, same as
    the journal's crash-safety contract)."""
    return os.fspath(journal_path) + ROLLUP_SUFFIX


def rollup_files(base: str) -> list[str]:
    """Every sidecar belonging to the journal at ``base`` (the base
    writer's plus fleet siblings')."""
    base = os.fspath(base)
    pat = re.compile(
        re.escape(os.path.basename(base)) + r"(\.[ws]\d+)?"
        + re.escape(ROLLUP_SUFFIX) + "$"
    )
    found = [
        p for p in glob.glob(glob.escape(base) + "*")
        if pat.fullmatch(os.path.basename(p))
    ]
    return sorted(found)


def read_rollups(base: str) -> list[dict]:
    """All intact rollup records for the journal at ``base`` (or, when
    ``base`` IS a sidecar file, that one file), ordered by window
    start.  Torn lines are skipped, like the journal's readers."""
    base = os.fspath(base)
    paths = ([base] if base.endswith(ROLLUP_SUFFIX)
             and os.path.isfile(base) else rollup_files(base))
    records: list[dict] = []
    for path in paths:
        try:
            f = open(path, "rb")
        except OSError:
            continue
        with f:
            for raw in f:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("schema"):
                    records.append(rec)
    records.sort(key=lambda r: (r.get("t0", 0.0), r.get("t1", 0.0)))
    return records


def merge_digest_snapshots(snaps: list[dict]) -> dict | None:
    """Count-weighted combine of WindowedDigest snapshots (the same
    estimate ``obs/slo.WindowedDigest.snapshot`` makes across its own
    cells): counts and sums add, max maxes, quantiles average
    count-weighted.  Rollup records carry ``new_count``/``new_sum`` —
    the growth since the previous flush — because the sliding SLO
    window overlaps successive rollup windows; merging those deltas
    makes the run-level count and sum EXACT, while the quantiles stay
    the usual load-homogeneous estimate."""

    def weight(s: dict) -> int:
        return int(s.get("new_count", s.get("count", 0)) or 0)

    def part_sum(s: dict) -> float:
        if "new_sum" in s:
            return float(s["new_sum"])
        return float(s.get("sum", 0.0) or 0.0)

    snaps = [s for s in snaps if s and weight(s) > 0]
    if not snaps:
        return None
    total = sum(weight(s) for s in snaps)
    out: dict[str, Any] = {
        "count": total,
        "sum": round(sum(part_sum(s) for s in snaps), 6),
        "max": max(float(s.get("max", 0.0)) for s in snaps),
    }
    out["mean"] = round(out["sum"] / total, 6)
    qkeys = sorted({k for s in snaps for k in s
                    if re.fullmatch(r"p\d+", k)})
    for q in qkeys:
        num = sum(float(s[q]) * weight(s) for s in snaps if q in s)
        den = sum(weight(s) for s in snaps if q in s)
        if den:
            out[q] = round(num / den, 6)
    stat = next((s["stat"] for s in reversed(snaps) if s.get("stat")),
                None)
    if stat is not None:
        out["stat"] = stat
    return out


def reconstruct(records: list[dict]) -> dict:
    """One run document out of a sidecar set: counters summed (exact —
    they were written as per-window deltas of monotonic counters),
    event counts and serve/train volume summed, gauges maxed, digests
    count-weight merged, data sketches last-wins (they are cumulative/
    windowed, not deltas), excursion intervals concatenated with the
    final record's still-open set."""
    doc: dict[str, Any] = {
        "schema": "stpu.obs.report/1",
        "windows": len(records),
        "t0": None, "t1": None,
        "writers": [],
        "jobs": [],
        "events": {},
        "counters": {},
        "serve": {},
        "train": {},
        "gauges": {},
        "compile": {},
        "data": {},
        "digests": {},
        "excursions": [],
        "open_excursions": [],
    }
    writers: set = set()
    jobs: set = set()
    digest_parts: dict[str, list[dict]] = {}
    open_by_writer: dict[tuple, list[dict]] = {}
    for rec in records:
        t0, t1 = rec.get("t0"), rec.get("t1")
        if t0 is not None:
            doc["t0"] = t0 if doc["t0"] is None else min(doc["t0"], t0)
        if t1 is not None:
            doc["t1"] = t1 if doc["t1"] is None else max(doc["t1"], t1)
        wkey = (rec.get("plane"), rec.get("worker"))
        writers.add(wkey)
        if rec.get("job"):
            jobs.add(rec["job"])
        for ev, n in (rec.get("events") or {}).items():
            doc["events"][ev] = doc["events"].get(ev, 0) + int(n)
        for src, deltas in (rec.get("counters") or {}).items():
            acc = doc["counters"].setdefault(src, {})
            for k, d in deltas.items():
                acc[k] = round(acc.get(k, 0.0) + float(d), 6)
        for model, m in (rec.get("serve") or {}).items():
            acc = doc["serve"].setdefault(model, {})
            for k, v in m.items():
                acc[k] = round(acc.get(k, 0) + v, 6)
        for wk, t in (rec.get("train") or {}).items():
            acc = doc["train"].setdefault(wk, {})
            for k, v in t.items():
                acc[k] = round(acc.get(k, 0) + v, 6)
        for k, v in (rec.get("gauges") or {}).items():
            doc["gauges"][k] = max(doc["gauges"].get(k, 0.0), float(v))
        for k, v in (rec.get("compile") or {}).items():
            if k == "max_s":
                doc["compile"][k] = max(doc["compile"].get(k, 0.0), v)
            else:
                doc["compile"][k] = round(
                    doc["compile"].get(k, 0) + v, 6)
        for k, v in (rec.get("data") or {}).items():
            doc["data"][k] = v  # last wins (records are time-ordered)
        for sig, snap in (rec.get("digests") or {}).items():
            digest_parts.setdefault(sig, []).append(snap)
        # excursions are per-WRITER state (each compactor tracked its
        # own journal): tag them, or worker A's recovery would hide
        # worker B's still-open excursion of the same signal
        wtag = (f"{rec.get('plane') or '?'}"
                + (f"/w{rec['worker']}" if rec.get("worker") is not None
                   else ""))
        doc["excursions"].extend(
            {**e, "writer": wtag} for e in rec.get("excursions") or [])
        # still-open excursions: each writer's LAST record's view wins
        open_by_writer[wkey] = [
            {**e, "writer": wtag}
            for e in rec.get("open_excursions") or []]
    for sig, parts in digest_parts.items():
        merged = merge_digest_snapshots(parts)
        if merged is not None:
            doc["digests"][sig] = merged
    still_open = [e for lst in open_by_writer.values() for e in lst]

    def _still_open(e: dict) -> bool:
        # a snapshot that a LATER window's completed interval covers is
        # not open anymore — matched per WRITER: another worker's
        # recovery says nothing about this one's excursion
        s = e.get("start_ts") or 0
        key = (e.get("writer"), e.get("kind"), e.get("name"))
        return not any(
            (c.get("writer"), c.get("kind"), c.get("name")) == key
            and (c.get("end_ts") or 0) >= s
            for c in doc["excursions"]
        )

    doc["open_excursions"] = [e for e in still_open if _still_open(e)]
    doc["writers"] = sorted(
        f"{p or '?'}" + (f"/w{w}" if w is not None else "")
        for p, w in writers)
    doc["jobs"] = sorted(jobs)
    return doc


def load_baseline(path: str) -> dict | None:
    """A pinned baseline run document: ``path`` is either one sidecar
    file or a journal base whose sidecars exist.  None when nothing is
    readable (the caller logs and runs without a baseline rather than
    failing the job)."""
    records = read_rollups(path)
    if not records:
        return None
    return reconstruct(records)


# ---- cross-run regression watchdog -------------------------------------------

#: digest-backed signals compared across runs, with the stat that
#: matters for each (falls back to the snapshot's recorded stat)
_REGRESSION_STATS = {"serve_p99_s": "p99", "train_step_ms": "mean"}

#: noise-discount scale: a live/baseline delta must clear
#: NOISE_K/sqrt(min(n_live, n_base)) above 1 before it can count —
#: the small-sample discipline the data-drift scorer uses (≈3/√n)
_NOISE_K = 3.0


class _RegState:
    __slots__ = ("breached", "bad", "good", "since")

    def __init__(self):
        self.breached = False
        self.bad = 0
        self.good = 0
        self.since: float | None = None


class RegressionWatchdog:
    """Live-vs-pinned-baseline comparison, evaluated on the serve SLO
    tick / train epoch tick.  Hysteretic like every other obs state
    machine; an absent live window (no traffic) counts as a clean tick
    so a drained fleet recovers."""

    def __init__(self, baseline: dict, *, threshold: float,
                 hysteresis: int = 2, min_count: int = 16,
                 plane: str = "serve", worker: int | None = None):
        if threshold <= 1:
            raise ValueError(
                f"regression threshold must be > 1, got {threshold}")
        self.baseline = baseline.get("digests") or {}
        self.threshold = float(threshold)
        self.hysteresis = max(1, int(hysteresis))
        self.min_count = max(1, int(min_count))
        self.plane = plane
        self.worker = worker
        self._states: dict[str, _RegState] = {}
        self._lock = threading.Lock()

    def _live_snapshots(self) -> dict[str, dict]:
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        wd = obs_slo.active()
        if wd is None:
            return {}
        try:
            return wd.digest_snapshots()
        except Exception:
            return {}

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One tick; returns (and journals) the events it emitted."""
        from shifu_tensorflow_tpu.obs import journal as obs_journal

        now = time.monotonic() if now is None else now
        live = self._live_snapshots()
        events: list[dict] = []
        with self._lock:
            for metric, base in self.baseline.items():
                stat = _REGRESSION_STATS.get(
                    metric, base.get("stat") or "mean")
                base_v = base.get(stat)
                base_n = int(base.get("count", 0) or 0)
                if base_v is None or base_v <= 0 or not base_n:
                    continue
                st = self._states.setdefault(metric, _RegState())
                snap = live.get(metric)
                live_v = snap.get(stat) if snap else None
                live_n = int(snap.get("count", 0) or 0) if snap else 0
                if live_v is not None and 0 < live_n < self.min_count:
                    # too few samples to judge either way: a NEUTRAL
                    # tick — neither opens nor closes an excursion.
                    # (Counting it clean once cleared a live 28×
                    # regression whose window just happened to be thin
                    # because the slowdown itself throttled traffic.)
                    continue
                regressing = False
                ratio = None
                if live_v is not None:
                    ratio = live_v / base_v
                    # noise-aware: the excess over 1 must clear the
                    # small-sample discount on top of the threshold
                    floor = (self.threshold - 1.0
                             + _NOISE_K / math.sqrt(min(live_n, base_n)))
                    regressing = ratio - 1.0 >= floor
                if regressing:
                    st.bad += 1
                    st.good = 0
                    if not st.breached and st.bad >= self.hysteresis:
                        st.breached = True
                        st.since = now
                        events.append({
                            "event": "perf_regression",
                            "metric": metric, "stat": stat,
                            "value": round(live_v, 6),
                            "baseline": round(base_v, 6),
                            "ratio": round(ratio, 4),
                            "threshold": self.threshold,
                        })
                else:
                    st.good += 1
                    st.bad = 0
                    if st.breached and st.good >= self.hysteresis:
                        st.breached = False
                        events.append({
                            "event": "perf_regression_clear",
                            "metric": metric, "stat": stat,
                            "value": (round(live_v, 6)
                                      if live_v is not None else None),
                            "baseline": round(base_v, 6),
                            "regression_s": round(
                                now - (st.since or now), 3),
                        })
                        st.since = None
        for ev in events:
            fields = {k: v for k, v in ev.items() if k != "event"}
            obs_journal.emit(ev["event"], plane=self.plane,
                             worker=self.worker, **fields)
        return events

    def state(self) -> dict[str, dict]:
        with self._lock:
            return {m: {"breached": st.breached}
                    for m, st in self._states.items()}


# ---- process-global hooks ----------------------------------------------------

_active: RollupCompactor | None = None
_regression: RegressionWatchdog | None = None


def install(compactor: RollupCompactor) -> RollupCompactor:
    global _active
    if _active is not None and _active is not compactor:
        _active.close()
    _active = compactor
    return compactor


def uninstall() -> None:
    global _active
    if _active is not None:
        _active.close()
    _active = None


def active() -> RollupCompactor | None:
    return _active


def install_regression(watchdog: RegressionWatchdog) -> RegressionWatchdog:
    global _regression
    _regression = watchdog
    return watchdog


def uninstall_regression() -> None:
    global _regression
    _regression = None


def regression_active() -> RegressionWatchdog | None:
    return _regression


def tick(now: float | None = None) -> None:
    """The slow-path hook the serve SLO loop and the trainer's epoch
    call: evaluate the regression watchdog (a no-op without a pinned
    baseline) — the compactor flushes on its own thread."""
    rw = _regression
    if rw is not None:
        try:
            rw.evaluate(now)
        except Exception:
            log.warning("regression evaluation failed", exc_info=True)
