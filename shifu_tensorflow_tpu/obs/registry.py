"""Thread-safe metrics primitives + the one Prometheus text renderer.

The single implementation behind every scrape surface in the framework:
``serve/metrics.py`` (the scoring server's ``/metrics``) and the
coordinator's fleet metrics (``metrics`` RPC op) both compose these
types, so counters, gauges, and latency summaries render in the same
Prometheus text exposition format everywhere — no third copy of a
histogram can appear (the serve and coordinator copies this replaced
had already started to drift in docstring only; one more subsystem and
they would have drifted in math).

Design style is the EpochAggregator discipline the originals followed:
one lock per primitive, explicit snapshots, no background machinery —
``record()`` on the hot path is one bisect + one increment.
"""

from __future__ import annotations

import bisect
import sys
import threading

#: default latency ladder: ~100µs .. 60s, roughly ×2 per bucket — wide
#: enough for a jitted dispatch at the bottom and a shed/overload tail at
#: the top, coarse enough that record() is one bisect + one increment.
#: Overridable per registry via ``shifu.tpu.obs-hist-buckets``.
DEFAULT_BOUNDS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: process-wide bucket ladder (shifu.tpu.obs-hist-buckets): installed by
#: obs.install_obs BEFORE the scrape surfaces construct their registries
#: (both CLIs resolve obs first), so ServeMetrics and the coordinator
#: pick the configured ladder up without threading it through every
#: constructor
_default_bounds: tuple[float, ...] = DEFAULT_BOUNDS


def set_default_bounds(bounds: tuple[float, ...] | None) -> None:
    global _default_bounds
    _default_bounds = tuple(bounds) if bounds else DEFAULT_BOUNDS


def default_bounds() -> tuple[float, ...]:
    return _default_bounds


def escape_label_suffix(name: str) -> str:
    """Tenant/model name → Prometheus-metric-name-legal suffix, used by
    every surface that folds a name into a GAUGE NAME (per-tenant SLO
    signals, per-model device bytes, per-model data-drift scores).  The
    escape is BIJECTIVE — '_' doubles, any other char outside
    [A-Za-z0-9] becomes two hex digits — so names differing only in
    '.', '-' vs '_' ("a.b" vs "a_b") cannot collide onto one gauge and
    silently overwrite each other's state.  ONE home on purpose: a fix
    applied to one leg's copy but not another's would render the same
    tenant to different suffixes across scrape surfaces and break every
    dashboard join on the name."""
    out = []
    for ch in name:
        if ch.isascii() and ch.isalnum():
            out.append(ch)
        elif ch == "_":
            out.append("__")
        else:
            out.append("_%02x" % ord(ch))
    return "".join(out)


_build_info_cache: dict[str, str] = {}


def build_info_text(prefix: str = "stpu_") -> str:
    """The ``stpu_build_info`` gauge: one constant-1 series whose labels
    say WHAT is running — package version, jax/jaxlib versions, backend
    platform — appended to every ``/metrics`` surface (serve workers,
    the coordinator ``metrics`` op) so a scrape identifies the build
    without shelling into the container.

    Versions are gathered lazily and cached for the process lifetime.
    jax is probed only if ALREADY IMPORTED, and the backend only if
    already initialized — a scrape must never pay jax import or backend
    startup (the coordinator's metrics op can run in a process that
    never touches a device).  The cache deliberately re-resolves while
    any field is still unknown, so the first scrape after jax comes up
    fills it in."""
    cached = _build_info_cache.get(prefix)
    if cached is not None and "unknown" not in cached:
        return cached
    version = jax_v = jaxlib_v = backend = "unknown"
    try:
        import shifu_tensorflow_tpu as pkg

        version = getattr(pkg, "__version__", None) or "unknown"
    except Exception:
        pass
    if version == "unknown":
        try:
            from importlib import metadata

            version = metadata.version("shifu-tensorflow-tpu")
        except Exception:
            pass
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_v = getattr(jax_mod, "__version__", "unknown")
        jaxlib_mod = sys.modules.get("jaxlib")
        if jaxlib_mod is not None:
            jaxlib_v = getattr(jaxlib_mod, "__version__", "unknown")
        try:
            xb = sys.modules.get("jax._src.xla_bridge")
            if xb is not None and getattr(xb, "_default_backend",
                                          None) is not None:
                backend = jax_mod.default_backend()
        except Exception:
            pass
    text = (
        f'# TYPE {prefix}build_info gauge\n'
        f'{prefix}build_info{{version="{version}",jax="{jax_v}",'
        f'jaxlib="{jaxlib_v}",backend="{backend}"}} 1\n'
    )
    _build_info_cache[prefix] = text
    return text


class LatencyHistogram:
    """Fixed-bound latency histogram with thread-safe record and quantile
    estimation.

    Quantiles come from the bucket upper bound containing the requested
    rank — conservative (never under-reports) and O(buckets), which is
    what a per-request hot path can afford."""

    def __init__(self, bounds: tuple[float, ...] | None = None):
        self._bounds = tuple(bounds) if bounds else _default_bounds
        # +1 overflow bucket for observations past the last bound
        self._counts = [0] * (len(self._bounds) + 1)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        i = bisect.bisect_left(self._bounds, seconds)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += seconds

    def percentile(self, p: float) -> float:
        """Upper bound of the bucket holding the p-th percentile (p in
        [0, 100]); 0.0 when nothing has been recorded."""
        with self._lock:
            if self._count == 0:
                return 0.0
            rank = max(1, int(round(self._count * p / 100.0)))
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= rank:
                    return (self._bounds[i] if i < len(self._bounds)
                            else float("inf"))
        return float("inf")

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "buckets": {
                    (str(b) if i < len(self._bounds) else "+Inf"): c
                    for i, (b, c) in enumerate(
                        zip(self._bounds + (float("inf"),), self._counts)
                    )
                },
            }


class MetricsRegistry:
    """Named counters + gauges + latency histograms with one renderer.

    Counters are pre-registrable (``counter(name)``) so a scrape surface
    can expose its full set from the first request — a counter that
    appears only after its first event breaks dashboards.  Gauges are
    set-at-render-time by convention (they belong to live objects — a
    queue, a model store — and pulling them at render keeps the registry
    dependency-free, the same argument serve/metrics.py already made).

    Rendering order is deterministic: counters sorted by name, then
    gauges and histogram summaries in registration order — so two
    registries fed the same way render byte-identical text.
    """

    def __init__(self, bounds: tuple[float, ...] | None = None):
        self._lock = threading.Lock()
        self._bounds = tuple(bounds) if bounds else _default_bounds
        self._counters: dict[str, int] = {}
        # name -> (labels, value); one label set per gauge name — a
        # re-set with fresh labels (e.g. model_info after a hot reload)
        # REPLACES the old series instead of accumulating stale ones
        self._gauges: dict[str, tuple[str, float]] = {}
        self._hists: dict[str, LatencyHistogram] = {}

    # ---- counters ----
    def counter(self, name: str) -> None:
        """Pre-register ``name`` at 0 so it renders before any event."""
        with self._lock:
            self._counters.setdefault(name, 0)

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # ---- gauges ----
    def set_gauge(self, name: str, value: float, labels: str = "") -> None:
        """``labels`` is a pre-rendered Prometheus label block, e.g.
        ``'{digest="abc"}'`` — empty for an unlabeled gauge."""
        with self._lock:
            self._gauges[name] = (labels, value)

    def remove_gauge(self, name: str) -> None:
        """Drop a gauge series (no-op when absent): a gauge whose
        subject is GONE — an evicted serve tenant's SLO signal — must
        leave the scrape rather than freeze at its last value."""
        with self._lock:
            self._gauges.pop(name, None)

    # ---- histograms ----
    def histogram(self, name: str,
                  bounds: tuple[float, ...] | None = None) -> LatencyHistogram:
        """Create-or-get the histogram registered under ``name``."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = LatencyHistogram(bounds or self._bounds)
                self._hists[name] = h
            return h

    # ---- rendering ----
    def render_prometheus(self, prefix: str, extra_labels: str = "") -> str:
        """The scrape body: every counter (sorted), gauge, and histogram
        summary under ``prefix`` (e.g. ``"stpu_serve_"``).

        ``extra_labels`` is a pre-rendered label *body* (no braces, e.g.
        ``'model="alpha"'``) merged into EVERY series this registry
        renders — the multi-tenant serve plane renders one registry per
        model and stamps the model dimension here, so per-model and
        single-model scrapes share one code path (and the default
        ``extra_labels=""`` render stays byte-identical to pre-tenancy
        output)."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._hists.items())

        def lbl(existing: str = "") -> str:
            # merge an existing pre-rendered block ('{digest="..."}' or
            # 'quantile="0.99"'-style bodies below) with the extra body
            body = existing.strip("{}")
            parts = [p for p in (body, extra_labels) if p]
            return "{%s}" % ",".join(parts) if parts else ""

        lines: list[str] = []
        for name, value in counters:
            lines.append(f"# TYPE {prefix}{name} counter")
            lines.append(f"{prefix}{name}{lbl()} {value}")
        for name, (labels, value) in gauges:
            lines.append(f"# TYPE {prefix}{name} gauge")
            lines.append(f"{prefix}{name}{lbl(labels)} {value}")
        for name, hist in hists:
            snap = hist.snapshot()
            lines.append(f"# TYPE {prefix}{name} summary")
            for q in (50, 90, 99):
                lines.append(
                    '%s%s%s %g'
                    % (prefix, name, lbl('quantile="0.%02d"' % q),
                       hist.percentile(q))
                )
            # real CUMULATIVE buckets beside the quantile gauges: the
            # quantiles above are bucket upper bounds (convenient but
            # ladder-quantized), while the _bucket series lets an
            # external Prometheus run histogram_quantile() itself —
            # cumulative counts, +Inf == _count, per the exposition
            # format's histogram convention
            acc = 0
            for bound, c in snap["buckets"].items():
                acc += c
                lines.append(
                    '%s%s_bucket%s %d'
                    % (prefix, name, lbl('le="%s"' % bound), acc)
                )
            lines.append(f"{prefix}{name}_count{lbl()} {snap['count']}")
            lines.append(f"{prefix}{name}_sum{lbl()} {snap['sum']:.6f}")
        return "\n".join(lines) + "\n"

