"""On-demand ``jax.profiler`` capture windows, journaled.

``utils/profiling.trace_if`` could always wrap a whole run in a
profiler trace — but a *production* question ("why did p99 double five
minutes ago?") needs a capture you can start against a RUNNING fleet,
bounded in time, whose dump you can later find.  This module is that
promotion:

- :func:`request` (what ``obs profile --request`` calls) drops a small
  JSON trigger file beside the fleet's journal base —
  ``<journal>.profile-request`` — naming the dump directory and the
  window length.  Writing a file is the one RPC every plane already
  shares (they all own the journal directory), and it works from a
  jax-free operator CLI.
- :func:`poll` runs on the planes' existing slow ticks (the trainer's
  per-epoch obs hook, the serve SLO evaluator thread).  The first
  poller to see the trigger consumes it (one capture per request, by
  design — ``worker`` in the trigger pins a specific worker index) and
  runs ``jax.profiler.start_trace``/``stop_trace`` for the requested
  window on a background thread, journaling ``profile_capture`` events
  at start and completion with the dump path — the pointer ``obs
  profile`` renders from a dead fleet's files.

Off-by-default-cheap: an un-configured process never stats anything;
a configured one pays one ``os.path.exists`` per slow tick.
stdlib-only at import; jax loads inside the capture thread.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs")

__all__ = ["configure", "unconfigure", "trigger_path", "request", "poll"]

_lock = threading.Lock()
_trigger: str | None = None     # trigger file this process polls
_worker: int | None = None      # this process's worker index
_plane: str = "train"
_capturing = False


def trigger_path(journal_base: str) -> str:
    """Where a capture request for the fleet journaled at ``base``
    lives (one well-known name: the CLI writes it, the planes poll)."""
    return f"{os.fspath(journal_base)}.profile-request"


def configure(journal_base: str | None, *, plane: str = "train",
              worker: int | None = None) -> None:
    """Arm polling for this process (install_obs calls this whenever a
    journal is configured — the journal base is the rendezvous)."""
    global _trigger, _worker, _plane
    with _lock:
        _trigger = trigger_path(journal_base) if journal_base else None
        _worker = worker
        _plane = plane


def unconfigure() -> None:
    configure(None)


def request(journal_base: str, out_dir: str, *, seconds: float = 5.0,
            worker: int | None = None) -> str:
    """Write the trigger (the ``obs profile --request`` body).  Returns
    the trigger path.  ``worker`` restricts which worker may consume it
    (None = first poller wins)."""
    path = trigger_path(journal_base)
    body: dict[str, Any] = {"dir": os.fspath(out_dir),
                            "seconds": float(seconds),
                            "requested_ts": round(time.time(), 3)}
    if worker is not None:
        body["worker"] = int(worker)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(body, f)
    os.replace(tmp, path)  # atomic: a poller never reads a torn trigger
    return path


def poll() -> bool:
    """One slow-tick check; True when this call consumed a trigger and
    started a capture.  Never raises — a broken trigger file is removed
    and logged, not allowed to wedge the tick that polls it."""
    global _capturing
    trig = _trigger
    if trig is None or not os.path.exists(trig):
        return False
    with _lock:
        if _capturing:
            return False
        try:
            with open(trig) as f:
                body = json.load(f)
        except (OSError, ValueError) as e:
            log.warning("removing unreadable profile trigger %s (%s)",
                        trig, e)
            _remove(trig)
            return False
        want = body.get("worker")
        if want is not None and _worker is not None and int(want) != _worker:
            return False  # addressed to a sibling; leave it for them
        # consume by ATOMIC CLAIM, not unlink: sibling fleet processes
        # poll the same path on independent ticks, and a read-then-unlink
        # window would let two of them both start the capture.  rename is
        # atomic on POSIX — exactly one poller wins; the losers see
        # FileNotFoundError and walk away.
        claim = f"{trig}.claim.{os.getpid()}"
        try:
            os.rename(trig, claim)
        except OSError:
            return False  # a sibling claimed it first
        _remove(claim)
        out_dir = body.get("dir") or os.path.dirname(trig) or "."
        seconds = max(0.1, float(body.get("seconds", 5.0)))
        _capturing = True
    t = threading.Thread(target=_capture, args=(out_dir, seconds),
                         name="obs-profile-capture", daemon=True)
    t.start()
    return True


def _remove(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _capture(out_dir: str, seconds: float) -> None:
    """The capture window itself (background thread: the profiler traces
    the whole process, so the polling thread need not stall for it)."""
    from shifu_tensorflow_tpu.obs import journal as obs_journal

    global _capturing
    t0 = time.time()
    try:
        import jax

        os.makedirs(out_dir, exist_ok=True)
        obs_journal.emit("profile_capture", plane=_plane, worker=_worker,
                         status="started", dir=out_dir, seconds=seconds)
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        obs_journal.emit("profile_capture", plane=_plane, worker=_worker,
                         status="done", dir=out_dir,
                         wall_s=round(time.time() - t0, 3))
    except Exception as e:
        log.warning("profiler capture to %s failed (%s: %s)",
                    out_dir, type(e).__name__, e)
        obs_journal.emit("profile_capture", plane=_plane, worker=_worker,
                         status="failed", dir=out_dir,
                         error=f"{type(e).__name__}: {e}")
    finally:
        with _lock:
            _capturing = False
