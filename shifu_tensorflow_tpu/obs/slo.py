"""SLO watchdog: windowed quantile digests, breach/recover events, and
an EWMA-z anomaly detector.

The PR-4 obs plane answers *aggregate* questions ("what fraction of the
step went to infeed?"); this module answers *temporal* ones ("is p99
degrading RIGHT NOW?") and turns the answer into journaled state
transitions (``slo_breach`` / ``slo_recover``) a supervisor policy can
act on — the tf.data lesson (arxiv 2101.12127) applied to the control
plane: close loops from live measured signals, not hand-set thresholds
read once at startup.

Three pieces, all stdlib, all bounded-memory:

- :class:`P2Quantile` — the P² streaming quantile estimator (Jain &
  Chlamtác 1985): five markers, O(1) update, no samples retained.
  Unlike :class:`obs.registry.LatencyHistogram` — whose ``percentile``
  returns the UPPER BOUND of the bucket holding the rank (conservative,
  ladder-quantized) — P² interpolates a point estimate, so a p99 moving
  *within* one histogram bucket is still visible to the watchdog.
- :class:`WindowedDigest` — a sliding window as a ring of time-bucket
  cells, each holding one P² estimator per tracked quantile plus
  count/sum/max.  Old cells expire by falling out of the ring; the
  window statistic merges live cells (count-weighted for quantiles — an
  estimate, exact when the cells are load-homogeneous).  Memory is
  O(buckets × quantiles), independent of request rate.
- :class:`EwmaZ` — EWMA mean/variance tracker producing a z-score per
  observation, for the "no target configured but this just jumped 6σ"
  case (``slo_anomaly`` events).

:class:`SloWatchdog` composes them: ``observe``/``count`` on the hot
path (one lock + a handful of float ops), ``evaluate`` on a slow tick
(serve: a background thread; train: per epoch).  Breach detection is
hysteretic — ``slo-hysteresis`` consecutive breaching evaluations flip
to BREACHED (one ``slo_breach`` event carrying the offending window's
digest snapshot), the same count of clean evaluations flips back (one
``slo_recover`` with the breach duration).  A signal with no target
still feeds the anomaly detector and the ``stpu_slo_*`` gauges, which
every ``/metrics`` surface appends — the sensor the ROADMAP item-4
autoscaler consumes for free.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any

from shifu_tensorflow_tpu.obs.registry import (
    MetricsRegistry,
    escape_label_suffix,
)

__all__ = [
    "P2Quantile",
    "WindowedDigest",
    "WindowedCounter",
    "EwmaZ",
    "SloWatchdog",
    "from_config",
    "install",
    "uninstall",
    "active",
]

_mono = time.monotonic

def _gauge_name(signal: str) -> str:
    """Signal name → Prometheus-legal gauge name.  Per-tenant signals
    carry a ``:<model>`` suffix whose charset ([A-Za-z0-9._-]) is wider
    than metric names allow; the shared bijective escape
    (obs/registry.escape_label_suffix) guarantees tenants differing
    only in '.', '-' vs '_' ("a.b" vs "a_b") cannot collide onto one
    gauge and silently overwrite each other's breach state."""
    if ":" not in signal:
        return f"slo_{signal}"
    base, model = signal.split(":", 1)
    return f"slo_{base}_{escape_label_suffix(model)}"


class P2Quantile:
    """Streaming single-quantile estimator (the P² algorithm): five
    markers track the running quantile without storing observations.
    ``value()`` is a point estimate that converges to the true quantile;
    with fewer than five observations it falls back to the nearest-rank
    quantile of what it has."""

    __slots__ = ("p", "_q", "_n", "_np", "_dn", "count")

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {p}")
        self.p = p
        self.count = 0
        self._q: list[float] = []  # marker heights (first 5: raw sorted)
        self._n: list[int] = []    # marker positions
        self._np: list[float] = []  # desired positions
        self._dn = (0.0, p / 2.0, p, (1.0 + p) / 2.0, 1.0)

    def add(self, x: float) -> None:
        x = float(x)
        self.count += 1
        if self.count <= 5:
            bisect.insort(self._q, x)
            if self.count == 5:
                self._n = [0, 1, 2, 3, 4]
                self._np = [0.0, 2.0 * self.p, 4.0 * self.p,
                            2.0 + 2.0 * self.p, 4.0]
            return
        q, n = self._q, self._n
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            q[4] = x
            k = 3
        else:
            k = 3
            for i in range(1, 5):
                if x < q[i]:
                    k = i - 1
                    break
        for i in range(k + 1, 5):
            n[i] += 1
        for i in range(5):
            self._np[i] += self._dn[i]
        # adjust the three interior markers toward their desired positions
        for i in (1, 2, 3):
            d = self._np[i] - n[i]
            if (d >= 1.0 and n[i + 1] - n[i] > 1) or (
                d <= -1.0 and n[i - 1] - n[i] < -1
            ):
                step = 1 if d >= 0 else -1
                cand = self._parabolic(i, step)
                if not q[i - 1] < cand < q[i + 1]:
                    cand = self._linear(i, step)
                q[i] = cand
                n[i] += step

    def _parabolic(self, i: int, d: int) -> float:
        q, n = self._q, self._n
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: int) -> float:
        q, n = self._q, self._n
        return q[i] + d * (q[i + d] - q[i]) / (n[i + d] - n[i])

    def value(self) -> float | None:
        if self.count == 0:
            return None
        if self.count < 5:
            # nearest-rank on the raw (sorted) observations so far
            rank = max(0, min(len(self._q) - 1,
                              int(math.ceil(self.p * len(self._q))) - 1))
            return self._q[rank]
        return self._q[2]


class _Cell:
    """One time bucket of a sliding window."""

    __slots__ = ("start", "count", "sum", "max", "p2")

    def __init__(self, start: float, quantiles: tuple[float, ...]):
        self.start = start
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self.p2 = {q: P2Quantile(q) for q in quantiles}


class WindowedDigest:
    """Sliding-window streaming digest: the window splits into
    ``buckets`` time cells, each a P²-per-quantile digest; a cell whose
    ring slot comes around again is reset, so observations older than
    the window can never contribute.  ``snapshot`` merges live cells —
    quantiles combine count-weighted across cells (a bounded-memory
    estimate; exact when the cells saw similar distributions)."""

    def __init__(self, window_s: float = 60.0, buckets: int = 6,
                 quantiles: tuple[float, ...] = (0.5, 0.9, 0.99)):
        self.window_s = float(window_s)
        self.buckets = max(2, int(buckets))
        self.bucket_s = self.window_s / self.buckets
        self.quantiles = tuple(quantiles)
        self._cells: list[_Cell | None] = [None] * self.buckets
        self._lock = threading.Lock()
        # lifetime totals, MONOTONIC (the windowed count shrinks as
        # cells expire): the rollup compactor delta-izes these to get
        # exact per-window observation counts — a sliding count cannot
        # be delta-ized (shrinkage would read as a reset and re-count
        # the survivors)
        self._total = 0
        self._total_sum = 0.0

    def _cell(self, now: float) -> _Cell:
        start = (now // self.bucket_s) * self.bucket_s
        idx = int(now // self.bucket_s) % self.buckets
        cell = self._cells[idx]
        if cell is None or cell.start != start:
            cell = _Cell(start, self.quantiles)
            self._cells[idx] = cell
        return cell

    def add(self, x: float, now: float | None = None) -> None:
        now = _mono() if now is None else now
        with self._lock:
            cell = self._cell(now)
            cell.count += 1
            cell.sum += x
            if x > cell.max:
                cell.max = x
            for p2 in cell.p2.values():
                p2.add(x)
            self._total += 1
            self._total_sum += x

    def totals(self) -> tuple[int, float]:
        """Lifetime (count, sum) — monotonic even when the window is
        empty, so the rollup compactor's conservation bookkeeping can
        account observations whose window expired before a flush."""
        with self._lock:
            return self._total, self._total_sum

    def snapshot(self, now: float | None = None) -> dict | None:
        """Merged window statistics, or None when the window holds no
        observations (the signal is then "absent", not zero)."""
        now = _mono() if now is None else now
        with self._lock:
            live = [c for c in self._cells
                    if c is not None and now - c.start < self.window_s
                    and c.count > 0]
            total = sum(c.count for c in live)
            if not total:
                return None
            out: dict[str, Any] = {
                "count": total,
                "sum": sum(c.sum for c in live),
                "max": max(c.max for c in live),
                # lifetime totals (monotonic — see __init__); consumed
                # by the rollup compactor, stripped from its records
                "total_count": self._total,
                "total_sum": self._total_sum,
            }
            out["mean"] = out["sum"] / total
            for q in self.quantiles:
                est = [(c.count, c.p2[q].value()) for c in live]
                out[f"p{int(q * 100)}"] = (
                    sum(n * v for n, v in est if v is not None) / total
                )
            return out


class WindowedCounter:
    """Sliding-window event counter (same ring-of-cells discipline as
    :class:`WindowedDigest`, counts only) — rate signals like shed
    fraction divide two of these over the same window."""

    def __init__(self, window_s: float = 60.0, buckets: int = 6):
        self.window_s = float(window_s)
        self.buckets = max(2, int(buckets))
        self.bucket_s = self.window_s / self.buckets
        self._cells: list[list[float] | None] = [None] * self.buckets
        self._lock = threading.Lock()

    def add(self, n: int = 1, now: float | None = None) -> None:
        now = _mono() if now is None else now
        start = (now // self.bucket_s) * self.bucket_s
        idx = int(now // self.bucket_s) % self.buckets
        with self._lock:
            cell = self._cells[idx]
            if cell is None or cell[0] != start:
                cell = [start, 0]
                self._cells[idx] = cell
            cell[1] += n

    def total(self, now: float | None = None) -> int:
        now = _mono() if now is None else now
        with self._lock:
            return sum(
                c[1] for c in self._cells
                if c is not None and now - c[0] < self.window_s
            )


class EwmaZ:
    """EWMA mean/variance tracker: ``update(x)`` returns the z-score of
    ``x`` against the PRE-update statistics (so the excursion itself
    does not dilute its own detection), then folds ``x`` in.  Returns
    None during warm-up.  The std floor is relative — 2% of the larger
    of |mean| and |x| — so a near-constant signal doesn't alarm on
    float jitter (at the default 6σ an excursion must move ≥12% of the
    running mean to fire) and a signal sitting at exactly 0 (e.g. a
    shed rate before the first shed) yields a bounded z (≤50) instead
    of dividing by nothing."""

    def __init__(self, alpha: float = 0.2, warmup: int = 8):
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self._mean: float | None = None
        self._var = 0.0
        self._n = 0

    def update(self, x: float) -> float | None:
        self._n += 1
        if self._mean is None:
            self._mean = float(x)
            return None
        std = math.sqrt(max(0.0, self._var))
        floor = 1e-12 + 0.02 * max(abs(self._mean), abs(x))
        z = (x - self._mean) / max(std, floor)
        d = x - self._mean
        self._mean += self.alpha * d
        self._var = (1.0 - self.alpha) * (self._var + self.alpha * d * d)
        return z if self._n > self.warmup else None


class _TrackedSignal:
    __slots__ = ("name", "stat", "target", "unit", "num", "den",
                 "breached", "bad", "good", "since", "ewma", "anomalous")

    def __init__(self, name: str, stat: str, target: float, unit: str,
                 num: str | None = None, den: str | None = None):
        self.name = name
        self.stat = stat     # p50|p90|p99|mean|max|rate
        self.target = float(target)
        self.unit = unit
        self.num = num       # rate signals: numerator / denominator
        self.den = den       # counter names
        self.breached = False
        self.bad = 0
        self.good = 0
        self.since: float | None = None
        self.ewma = EwmaZ()
        self.anomalous = False


class SloWatchdog:
    """Windowed SLO evaluation with hysteresis and anomaly detection.

    Hot path: ``observe(signal, value)`` / ``count(name)`` — one digest
    or counter update.  Slow path: ``evaluate()`` — compute each tracked
    signal's window statistic, compare against its target, journal
    ``slo_breach`` / ``slo_recover`` transitions, update the
    ``stpu_slo_*`` gauges, and run the EWMA-z anomaly check.  Evaluation
    and observation may race freely (every structure locks internally).
    """

    def __init__(self, *, window_s: float = 60.0, hysteresis: int = 2,
                 anomaly_sigma: float = 6.0, plane: str = "train",
                 worker: int | None = None, buckets: int = 6):
        self.window_s = float(window_s)
        self.hysteresis = max(1, int(hysteresis))
        self.anomaly_sigma = float(anomaly_sigma)
        self.plane = plane
        self.worker = worker
        self.buckets = buckets
        self._signals: dict[str, _TrackedSignal] = {}
        self._digests: dict[str, WindowedDigest] = {}
        self._counters: dict[str, WindowedCounter] = {}
        # serve-plane targets remembered by from_config so per-tenant
        # signals registered AFTER construction (a model admitted at
        # runtime) inherit the configured targets
        self.serve_p99_target_s = 0.0
        self.serve_shed_rate_target = 0.0
        self._lock = threading.Lock()
        # serializes evaluate(): the breach state machine mutates
        # per-signal streak counters, and on the thread launcher several
        # trainers share one watchdog and tick it per epoch
        self._eval_lock = threading.Lock()
        self.registry = MetricsRegistry()

    # ---- registration ----
    def track(self, name: str, *, stat: str = "p99", target: float = 0.0,
              unit: str = "") -> None:
        """Track a value signal: window ``stat`` vs ``target`` (0 = no
        target — gauges + anomaly detection only)."""
        with self._lock:
            self._signals[name] = _TrackedSignal(name, stat, target, unit)
            self._digests.setdefault(
                name, WindowedDigest(self.window_s, self.buckets))

    def track_rate(self, name: str, *, num: str, den: str,
                   target: float = 0.0) -> None:
        """Track a ratio of two windowed counters (e.g. shed fraction:
        ``num="shed", den="requests"``)."""
        with self._lock:
            self._signals[name] = _TrackedSignal(
                name, "rate", target, "", num=num, den=den)
            self._counters.setdefault(
                num, WindowedCounter(self.window_s, self.buckets))
            self._counters.setdefault(
                den, WindowedCounter(self.window_s, self.buckets))

    def track_serve_tenant(self, model: str) -> None:
        """Register the per-tenant serve signals (idempotent): the
        tenancy store calls this on every admission, so each model gets
        its OWN windowed p99 and shed-rate state machine under the
        plane-wide targets — per-model ``slo_breach`` events name the
        tenant via the signal, and one hot tenant's breach does not
        paint the whole plane red.  Signal names use ``:`` as the model
        separator; the gauge renderer sanitizes it (Prometheus metric
        names can't carry it)."""
        p99 = f"serve_p99_s:{model}"
        with self._lock:
            if p99 in self._signals:
                return
        self.track(p99, stat="p99", target=self.serve_p99_target_s,
                   unit="s")
        self.track_rate(f"serve_shed_rate:{model}",
                        num=f"shed:{model}", den=f"requests:{model}",
                        target=self.serve_shed_rate_target)

    def untrack_serve_tenant(self, model: str) -> None:
        """Drop a tenant's signals and their gauges (eviction): the
        watchdog must not keep rendering a frozen p99 for a model that
        is no longer serving — the ROADMAP item-4 autoscaler reads
        these gauges.  A re-admission re-registers via
        :meth:`track_serve_tenant`.  Serialized with ``evaluate`` under
        the eval lock: an in-flight tick that already snapshotted this
        tenant's signal would otherwise re-set the gauges right after
        their removal, resurrecting them forever (no later tick would
        know the signal to clean up)."""
        p99 = f"serve_p99_s:{model}"
        rate = f"serve_shed_rate:{model}"
        with self._eval_lock:
            with self._lock:
                self._signals.pop(p99, None)
                self._signals.pop(rate, None)
                self._digests.pop(p99, None)
                self._counters.pop(f"shed:{model}", None)
                self._counters.pop(f"requests:{model}", None)
            for base in (p99, rate):
                g = _gauge_name(base)
                for suffix in ("", "_target", "_breached", "_z"):
                    self.registry.remove_gauge(g + suffix)

    # ---- hot path ----
    def observe(self, name: str, value: float) -> None:
        d = self._digests.get(name)
        if d is None:
            with self._lock:
                d = self._digests.setdefault(
                    name, WindowedDigest(self.window_s, self.buckets))
        d.add(value)

    def count(self, name: str, n: int = 1) -> None:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(
                    name, WindowedCounter(self.window_s, self.buckets))
        c.add(n)

    # ---- slow path ----
    def _value_of(self, sig: _TrackedSignal,
                  now: float) -> tuple[float | None, dict | None]:
        # .get, not []: an untrack (tenant eviction) can remove the
        # backing structures between evaluate()'s signal snapshot and
        # this read — an absent structure is an absent signal
        if sig.stat == "rate":
            den_c = self._counters.get(sig.den)
            num_c = self._counters.get(sig.num)
            if den_c is None or num_c is None:
                return None, None
            den = den_c.total(now)
            if den == 0:
                return None, None
            num = num_c.total(now)
            return num / den, {"count": den, sig.num: num}
        d = self._digests.get(sig.name)
        snap = d.snapshot(now) if d is not None else None
        if snap is None:
            return None, None
        return snap.get(sig.stat), snap

    def evaluate(self, now: float | None = None, **ctx: Any) -> list[dict]:
        """One evaluation tick.  Returns the events it emitted (also
        journaled via ``obs.journal.emit`` — a no-op without a journal).
        ``ctx`` fields (e.g. ``epoch=N``) ride every emitted event."""
        from shifu_tensorflow_tpu.obs import journal as obs_journal

        now = _mono() if now is None else now
        events: list[dict] = []
        with self._lock:
            signals = list(self._signals.values())
        with self._eval_lock:
            events = self._evaluate_locked(signals, now, ctx)
        for ev in events:
            fields = {k: v for k, v in ev.items() if k != "event"}
            obs_journal.emit(ev["event"], plane=self.plane,
                            worker=self.worker, **fields)
        return events

    def _evaluate_locked(self, signals: list[_TrackedSignal], now: float,
                         ctx: dict) -> list[dict]:
        events: list[dict] = []
        for sig in signals:
            value, snap = self._value_of(sig, now)
            # per-tenant signal names carry ':' (serve_p99_s:alpha) —
            # escaped bijectively for the gauge, Prometheus names can't
            # hold the tenant charset
            gname = _gauge_name(sig.name)
            if value is not None:
                self.registry.set_gauge(gname, round(value, 6))
            if sig.target > 0:
                self.registry.set_gauge(f"{gname}_target", sig.target)
            # hysteretic breach state machine.  An EMPTY window (value
            # None) never starts a breach, but DOES count as a clean
            # tick: a serve plane whose overload shed every client (no
            # samples once they give up) must still recover when the
            # window drains.
            breaching = (sig.target > 0 and value is not None
                         and value > sig.target)
            if breaching:
                sig.bad += 1
                sig.good = 0
                if not sig.breached and sig.bad >= self.hysteresis:
                    sig.breached = True
                    sig.since = now
                    ev = {
                        "event": "slo_breach", "signal": sig.name,
                        "value": round(value, 6), "target": sig.target,
                        "window_s": self.window_s,
                        "window": _round_snap(snap), **ctx,
                    }
                    events.append(ev)
            else:
                sig.good += 1
                sig.bad = 0
                if sig.breached and sig.good >= self.hysteresis:
                    sig.breached = False
                    ev = {
                        "event": "slo_recover", "signal": sig.name,
                        "value": (round(value, 6) if value is not None
                                  else None),
                        "target": sig.target,
                        "breach_s": round(now - (sig.since or now), 3),
                        **ctx,
                    }
                    sig.since = None
                    events.append(ev)
            self.registry.set_gauge(f"{gname}_breached", int(sig.breached))
            # EWMA-z anomaly: fires once per excursion past ±sigma, for
            # signals with no configured target too ("nobody set an SLO
            # but this just jumped 6σ")
            if self.anomaly_sigma > 0 and value is not None:
                z = sig.ewma.update(value)
                if z is not None:
                    self.registry.set_gauge(f"{gname}_z", round(z, 3))
                    if abs(z) >= self.anomaly_sigma and not sig.anomalous:
                        sig.anomalous = True
                        events.append({
                            "event": "slo_anomaly", "signal": sig.name,
                            "value": round(value, 6), "z": round(z, 2),
                            "sigma": self.anomaly_sigma, **ctx,
                        })
                    elif abs(z) < self.anomaly_sigma:
                        sig.anomalous = False
        return events

    # ---- reading ----
    def digest_snapshots(self, now: float | None = None) -> dict[str, dict]:
        """Current-window snapshot of every digest-backed signal that
        holds observations, each stamped with the signal's evaluation
        stat.  The rollup compactor records these per window and the
        regression watchdog compares them against a pinned baseline —
        a JSON-able read, no state mutated."""
        now = _mono() if now is None else now
        with self._lock:
            digests = list(self._digests.items())
            stats = {name: sig.stat for name, sig in self._signals.items()}
        out: dict[str, dict] = {}
        for name, d in digests:
            snap = d.snapshot(now)
            if snap is None:
                continue
            snap = _round_snap(snap)
            stat = stats.get(name)
            if stat is not None:
                snap["stat"] = stat
            out[name] = snap
        return out

    def digest_totals(self) -> dict[str, tuple[int, float]]:
        """Lifetime (count, sum) per digest-backed signal — monotonic
        and present even when a signal's window is EMPTY, unlike
        :meth:`digest_snapshots`.  The rollup compactor reads both: the
        snapshot for window statistics, the totals for conservation (an
        observation whose window expired before the flush still
        counts)."""
        with self._lock:
            digests = list(self._digests.items())
        return {name: d.totals() for name, d in digests}

    def state(self) -> dict[str, dict]:
        """Per-signal state snapshot (tests, /healthz embedding)."""
        with self._lock:
            signals = list(self._signals.values())
        now = _mono()
        out = {}
        for sig in signals:
            value, _ = self._value_of(sig, now)
            out[sig.name] = {
                "value": value, "target": sig.target,
                "breached": sig.breached, "stat": sig.stat,
            }
        return out

    def render_prometheus(self) -> str:
        """``stpu_slo_*`` gauge text, appended by every scrape surface
        (serve ``/metrics``, the coordinator ``metrics`` op)."""
        return self.registry.render_prometheus("stpu_")


def _round_snap(snap: dict | None) -> dict | None:
    if snap is None:
        return None
    return {k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in snap.items()}


def from_config(cfg, *, plane: str = "train",
                worker: int | None = None) -> SloWatchdog:
    """Build the plane's watchdog from a resolved ObsConfig: serve
    planes track request p99 + shed rate, train planes step time + the
    infeed-wait fraction of the step budget.  The coordinator plane
    registers the TRAIN signals too: on the thread launcher the workers
    share the submitter's process and pick up exactly this watchdog
    (Trainer reads slo.active()), so a coordinator-plane watchdog
    without them would silently drop the configured train targets; on
    the process launcher those digests just stay empty (nothing
    observes or evaluates them there — each subprocess worker runs its
    own).  Targets of 0 leave a signal untargeted (gauges + anomaly
    detection only) — the watchdog is always worth installing once obs
    is on.

    Both train signals are fed ONE sample per epoch (the same
    ``step_breakdown`` drain), so their window statistics are over
    epoch-level aggregates: ``train_step_ms`` is the windowed MEAN of
    per-epoch mean step wall time — not a per-step p99, which the
    tracer's aggregate span counters cannot provide."""
    wd = SloWatchdog(
        window_s=cfg.slo_window_s,
        hysteresis=cfg.slo_hysteresis,
        anomaly_sigma=cfg.slo_anomaly_sigma,
        plane=plane,
        worker=worker,
    )
    if plane == "serve":
        wd.track("serve_p99_s", stat="p99",
                 target=cfg.slo_serve_p99_ms / 1000.0, unit="s")
        wd.track_rate("serve_shed_rate", num="shed", den="requests",
                      target=cfg.slo_serve_shed_rate)
        # per-tenant signals reuse these targets when the multi-model
        # store admits a model at runtime (track_serve_tenant)
        wd.serve_p99_target_s = cfg.slo_serve_p99_ms / 1000.0
        wd.serve_shed_rate_target = cfg.slo_serve_shed_rate
    else:  # train — and coordinator, whose process may HOST trainers
        wd.track("train_step_ms", stat="mean",
                 target=cfg.slo_step_time_ms, unit="ms")
        wd.track("train_infeed_frac", stat="mean",
                 target=cfg.slo_infeed_frac)
        # fleet leg (obs/fleet.py): per-epoch MAX of per-rank relative
        # step-time skew, fed by the coordinator's FleetMonitor on each
        # epoch quorum — the signal ROADMAP item-3's standby-takeover /
        # autoscaler policy consumes.  Registered on the train plane
        # too for the thread launcher, where the coordinator and its
        # workers share one process-wide watchdog.
        wd.track("fleet_skew", stat="max",
                 target=getattr(cfg, "slo_straggler_skew", 0.0))
    # device/compiler signals ride EVERY plane: the compile flight
    # recorder feeds compile_s per compilation (window MAX — one slow
    # compile is the breach, an average of fast ones is not), and the
    # memory accountant feeds devmem_frac whenever the backend reports a
    # bytes limit (TPU/GPU; absent on CPU, so the signal stays absent
    # rather than reading 0 forever)
    wd.track("compile_s", stat="max",
             target=getattr(cfg, "slo_compile_s", 0.0), unit="s")
    wd.track("devmem_frac", stat="max",
             target=getattr(cfg, "slo_devmem_frac", 0.0))
    # data leg (obs/datastats.py): the drift monitor feeds the
    # fleet-wide MAX per-model drift score on every evaluation tick —
    # one drifted tenant IS the breach, an average against healthy
    # peers would hide it.  Every plane: serve is the primary feeder,
    # but a train-plane monitor comparing against a previous baseline
    # rides the same signal.
    wd.track("data_drift_score", stat="max",
             target=getattr(cfg, "slo_data_drift", 0.0))
    return wd


# ---- process-global hook (mirrors obs.trace / obs.journal) ----

_active: SloWatchdog | None = None


def install(watchdog: SloWatchdog) -> SloWatchdog:
    global _active
    _active = watchdog
    return watchdog


def uninstall() -> None:
    global _active
    _active = None


def active() -> SloWatchdog | None:
    return _active
