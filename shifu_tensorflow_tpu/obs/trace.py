"""Lightweight span timing for the hot paths.

tf.data (arxiv 2101.12127) showed input-pipeline stall time is the
dominant *invisible* training bottleneck; TF-Replicator (arxiv
1902.00465) showed per-replica timing through a common instrumentation
layer is what makes distributed-SGD regressions diagnosable.  This
module is that layer for the per-step loop: every epoch can report a
breakdown of

- ``step.host``      — producing the next host batch (parse/stack/filter),
- ``step.infeed``    — device placement (host-side gather/pad + transfer),
- ``step.dispatch``  — enqueueing the jitted step,
- ``step.block``     — fetching results (the only true completion wait
  on this backend — see utils/profiling.true_sync),

plus named spans around checkpoint save/restore (train/checkpoint.py),
retry backoff sleeps (utils/retry.py), and coordinator RPCs
(coordinator/coordinator.py).  Spans carry the worker index so SPMD
replicas can be compared side by side.

Cost discipline: a disabled site is ONE module-global load + ``is None``
check; an enabled site is two ``perf_counter`` calls and a dict update
under a lock (~1µs).  The trainer's per-step phases are all in one
thread, so contention is nil; the lock exists for the cross-thread
spans (retry sleeps on a checkpoint writer thread, RPC heartbeats).
``sample_every=N`` measures every Nth event per span name — steady-state
ratios stay unbiased while the (already tiny) cost divides by N.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Iterable, Iterator

__all__ = [
    "Tracer",
    "install",
    "uninstall",
    "active",
    "span",
    "record",
]

_perf = time.perf_counter


class Tracer:
    """Accumulating span sink: ``add(name, seconds)`` and sugar around it."""

    def __init__(self, worker_index: int = 0, sample_every: int = 1):
        self.worker_index = int(worker_index)
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        # name -> [count, total_s, max_s]; counts are MEASURED events
        # (under sampling, 1/sample_every of the real events)
        self._spans: dict[str, list] = {}
        # per-name call counter driving the sampling decision
        self._calls: dict[str, int] = {}

    # ---- recording ----
    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            s = self._spans.get(name)
            if s is None:
                self._spans[name] = [1, seconds, seconds]
            else:
                s[0] += 1
                s[1] += seconds
                if seconds > s[2]:
                    s[2] = seconds

    def _sampled(self, name: str) -> bool:
        # sampling exists to cut HOT-PATH cost, so it applies only to the
        # per-step phases; auxiliary spans (checkpoint.save, rpc.*, ...)
        # fire a handful of times per epoch and are always measured —
        # scaling them back up in budget_fields would overestimate the
        # rare events sampling never needed to skip
        if self.sample_every == 1 or not name.startswith("step."):
            return True
        with self._lock:
            n = self._calls.get(name, 0)
            self._calls[name] = n + 1
        return n % self.sample_every == 0

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        if not self._sampled(name):
            yield
            return
        t0 = _perf()
        try:
            yield
        finally:
            self.add(name, _perf() - t0)

    def timed(self, name: str, fn: Callable) -> Callable:
        """Wrap ``fn`` so each (sampled) call records a span."""

        def wrapper(*a, **kw):
            if not self._sampled(name):
                return fn(*a, **kw)
            t0 = _perf()
            try:
                return fn(*a, **kw)
            finally:
                self.add(name, _perf() - t0)

        return wrapper

    def wrap_iter(self, name: str, it: Iterable) -> Iterator:
        """Time each ``next()`` of ``it`` — how long producing the next
        item stalls the consumer."""
        it = iter(it)
        while True:
            if self._sampled(name):
                t0 = _perf()
                try:
                    item = next(it)
                except StopIteration:
                    return
                self.add(name, _perf() - t0)
            else:
                try:
                    item = next(it)
                except StopIteration:
                    return
            yield item

    # ---- reading ----
    def summary(self) -> dict[str, dict[str, float]]:
        """``name -> {count, total_s, mean_s, max_s}`` snapshot.  Under
        sampling, ``count``/``total_s`` cover the measured subset; the
        ``sampled_every`` field says by how much to scale absolute
        totals (ratios need no scaling)."""
        with self._lock:
            return {
                name: {
                    "count": s[0],
                    "total_s": s[1],
                    "mean_s": s[1] / s[0] if s[0] else 0.0,
                    "max_s": s[2],
                    "sampled_every": self.sample_every,
                }
                for name, s in self._spans.items()
            }

    def take_summary(self) -> dict[str, dict[str, float]]:
        """summary() + reset() under one lock acquisition — the per-epoch
        journal report uses this so no span can fall between the read
        and the clear."""
        with self._lock:
            spans, self._spans = self._spans, {}
            self._calls.clear()
        return {
            name: {
                "count": s[0],
                "total_s": s[1],
                "mean_s": s[1] / s[0] if s[0] else 0.0,
                "max_s": s[2],
                "sampled_every": self.sample_every,
            }
            for name, s in spans.items()
        }

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self._calls.clear()


# ---- process-global hook (the instrumented seams call these) ----

_active: Tracer | None = None
_NULL_CM = contextlib.nullcontext()


def install(tracer: Tracer) -> Tracer:
    global _active
    _active = tracer
    return tracer


def uninstall() -> None:
    global _active
    _active = None


def active() -> Tracer | None:
    return _active


def span(name: str):
    """``with obs_trace.span("checkpoint.save"): ...`` — no-op (a shared
    nullcontext, no allocation) when no tracer is installed."""
    t = _active
    return t.span(name) if t is not None else _NULL_CM


def maybe_span(tracer: Tracer | None, name: str):
    """Span on an explicit (possibly-None) tracer — the trainer's epoch
    paths hold their tracer in a local, so the hot loop pays one local
    load instead of a module-global read per phase."""
    return tracer.span(name) if tracer is not None else _NULL_CM


def record(name: str, seconds: float) -> None:
    """Record an already-measured duration (e.g. a retry backoff sleep
    whose length is known before it happens)."""
    t = _active
    if t is not None:
        t.add(name, seconds)


def budget_fields(summary: dict[str, dict[str, float]]) -> dict[str, Any]:
    """Flatten a tracer summary into the journal's ``step_breakdown``
    event schema: the four step phases as ``*_s`` totals + ``steps``
    (dispatch count), everything else under ``"spans"``.

    Under ``sample_every=N`` the step phases measured 1/N of the real
    events, so their totals and the step count scale back up by N here —
    the journal records unbiased ESTIMATES of the epoch's absolute
    phase times, which the CLI budget divides by the (unsampled) epoch
    wall clock.  Auxiliary spans are never sampled (see ``_sampled``)
    and pass through raw.  ``trace_sample`` is recorded whenever N>1 so
    a reader can tell an estimate from an exact total."""
    phases = {
        "infeed_s": "step.infeed",
        "host_s": "step.host",
        "dispatch_s": "step.dispatch",
        "block_s": "step.block",
    }
    out: dict[str, Any] = {}
    scale = 1
    for field_name, span_name in phases.items():
        s = summary.get(span_name)
        if s:
            scale = max(scale, int(s.get("sampled_every", 1)))
        out[field_name] = (
            round(s["total_s"] * s.get("sampled_every", 1), 6) if s else 0.0
        )
    # pipelined infeed splits the phase: "step.infeed.wait" is the
    # consumer-visible stall (counts toward the budget's infeed slice,
    # additive with any unthreaded step.infeed from other paths) while
    # "step.infeed.put" is put-thread placement work OVERLAPPING dispatch
    # (reported separately, never added to the phase total — the budget
    # divides by wall clock, and overlapped work would double-count).
    # `obs summary` renders the split so "starved" (wait-heavy) and
    # "placement-slow" (put-heavy) are distinguishable.
    w = summary.get("step.infeed.wait")
    if w:
        wait = round(w["total_s"] * w.get("sampled_every", 1), 6)
        out["infeed_wait_s"] = wait
        out["infeed_s"] = round(out["infeed_s"] + wait, 6)
        scale = max(scale, int(w.get("sampled_every", 1)))
    p = summary.get("step.infeed.put")
    if p:
        out["infeed_put_s"] = round(
            p["total_s"] * p.get("sampled_every", 1), 6)
        scale = max(scale, int(p.get("sampled_every", 1)))
    # "step.host.produce" is host-batch production that ran ON the put
    # thread (pipelined infeed) — overlapped with dispatch, so, exactly
    # like infeed_put_s, it reports separately and never joins the
    # disjoint wall-clock phases (host_s stays the consumer-visible
    # stall, which is 0 on that path by construction)
    hp = summary.get("step.host.produce")
    if hp:
        out["host_produce_s"] = round(
            hp["total_s"] * hp.get("sampled_every", 1), 6)
        scale = max(scale, int(hp.get("sampled_every", 1)))
    d = summary.get("step.dispatch")
    out["steps"] = int(d["count"] * d.get("sampled_every", 1)) if d else 0
    if scale > 1:
        out["trace_sample"] = scale
    extra = {
        name: {"count": int(s["count"]), "total_s": round(s["total_s"], 6)}
        for name, s in summary.items()
        if not name.startswith("step.")
    }
    if extra:
        out["spans"] = extra
    return out
