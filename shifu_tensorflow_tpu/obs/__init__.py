"""Unified observability plane: metrics registry, span tracing, event journal.

The reference system's only window into a running job was YARN container
logs and heartbeat exit codes — the AM could say *that* a worker died,
never *why it was slow* (SURVEY.md §5).  By PR 3 this reproduction had
grown three private telemetry planes (serve counters, the coordinator
epoch board, ad-hoc trainer log lines) with no shared vocabulary.  This
package is the one instrumentation layer all three planes share:

- :mod:`~shifu_tensorflow_tpu.obs.registry` — thread-safe counters,
  gauges, and latency histograms with one Prometheus text renderer.
  ``serve/metrics.py`` is a thin wrapper over these types.
- :mod:`~shifu_tensorflow_tpu.obs.trace` — lightweight span timing for
  the per-step loop (infeed / host / dispatch / block), checkpoint
  save/restore, retry sleeps, and coordinator RPCs.  Spans carry the
  worker index so SPMD replicas compare.
- :mod:`~shifu_tensorflow_tpu.obs.journal` — append-only JSONL event
  journal (rotation + size cap, crash-safe line-at-a-time writes) that
  records structured lifecycle events from train, coordinator, and
  serve.  ``python -m shifu_tensorflow_tpu.obs tail|summary`` reads it.

Everything is off-by-default-cheap: with no ``shifu.tpu.obs-*`` key set,
the module-level hooks are a single ``is None`` check per call site
(measured <2% step-time overhead even fully enabled — BENCH_OBS.json).
stdlib-only by design: the observability plane must import in every
process (CLI ``--help`` included) without paying for jax.
"""

from __future__ import annotations

from shifu_tensorflow_tpu.obs.config import ObsConfig, resolve_obs_config
from shifu_tensorflow_tpu.obs.registry import (
    LatencyHistogram,
    MetricsRegistry,
)

__all__ = [
    "ObsConfig",
    "resolve_obs_config",
    "LatencyHistogram",
    "MetricsRegistry",
    "install_obs",
    "device_obs_text",
]


def device_obs_text() -> str:
    """The device/compiler leg's scrape suffix, shared by every
    ``/metrics`` surface (serve single-model, serve multi-tenant, the
    coordinator ``metrics`` op): ``stpu_compile_*`` (the executable
    registry + storm state) and ``stpu_devmem_*`` (the memory
    accountant's last snapshot) when the leg is installed, plus —
    always — the ``stpu_build_info`` identity gauge saying WHAT build
    answered the scrape."""
    from shifu_tensorflow_tpu.obs import compile as compile_mod
    from shifu_tensorflow_tpu.obs import cost as cost_mod
    from shifu_tensorflow_tpu.obs import datastats as datastats_mod
    from shifu_tensorflow_tpu.obs import memory as memory_mod
    from shifu_tensorflow_tpu.obs.registry import build_info_text

    text = ""
    rec = compile_mod.active()
    if rec is not None:
        text += rec.render_prometheus()
    mem = memory_mod.active()
    if mem is not None:
        text += mem.render_prometheus()
    mon = datastats_mod.active()
    if mon is not None:
        # stpu_data_* per-model drift gauges (the data leg)
        text += mon.render_prometheus()
    acct = cost_mod.active()
    if acct is not None:
        # stpu_cost_* per-tenant device-time counters + the device
        # lane's busy/idle headroom gauges (the cost leg)
        text += acct.render_prometheus()
    return text + build_info_text()


def install_obs(cfg: ObsConfig, *, worker_index: int | None = None,
                plane: str = "train", job: str | None = None):
    """Install the process-wide tracer + journal + SLO watchdog from a
    resolved :class:`ObsConfig`.  Returns ``(tracer, journal)`` (either
    may be None; the watchdog is reachable via ``obs.slo.active()``).
    Subprocess workers pass their ``worker_index`` so their journal
    lands beside the base path as ``<path>.w<index>`` (train fleets) or
    ``<path>.s<index>`` (``--serve-workers`` scoring processes) — one
    writer per file keeps the line-at-a-time crash-safety contract
    honest across a fleet (the CLI reader merges the set by ``(ts,
    writer, seq)``).  ``job`` is the fleet-wide correlation id every
    event from this writer carries — mint one per job at the submitting
    CLI (workers receive it via the register reply / ``--obs-job``), so
    one merged journal can tell two jobs' events apart.
    """
    from shifu_tensorflow_tpu.obs import compile as compile_mod
    from shifu_tensorflow_tpu.obs import cost as cost_mod
    from shifu_tensorflow_tpu.obs import datastats as datastats_mod
    from shifu_tensorflow_tpu.obs import fleet as fleet_mod
    from shifu_tensorflow_tpu.obs import journal as journal_mod
    from shifu_tensorflow_tpu.obs import memory as memory_mod
    from shifu_tensorflow_tpu.obs import profile as profile_mod
    from shifu_tensorflow_tpu.obs import registry as registry_mod
    from shifu_tensorflow_tpu.obs import rollup as rollup_mod
    from shifu_tensorflow_tpu.obs import slo as slo_mod
    from shifu_tensorflow_tpu.obs import trace as trace_mod

    # persistent compilation cache (shifu.tpu.compile-cache-dir): a
    # compile-plane knob riding this config for the key-resolve + JSON
    # bridge, applied regardless of whether observability itself is on
    # (best-effort, no-op on jax-free hosts)
    if getattr(cfg, "compile_cache_dir", ""):
        compile_mod.apply_persistent_cache(cfg.compile_cache_dir)
    if not cfg.enabled:
        slo_mod.uninstall()
        compile_mod.uninstall()
        memory_mod.uninstall()
        fleet_mod.uninstall()
        datastats_mod.uninstall()
        datastats_mod.uninstall_train()
        cost_mod.uninstall()
        # drop the retired accountant's counter source too — the
        # process-global _sources dict would otherwise pin its object
        # graph for process lifetime (same leak the serve close path
        # guards against)
        rollup_mod.unregister_source("cost")
        rollup_mod.uninstall()
        rollup_mod.uninstall_regression()
        profile_mod.unconfigure()
        return None, None
    if cfg.hist_buckets:
        # scrape surfaces construct their registries AFTER the CLI
        # installs obs, so the configured ladder reaches them here
        registry_mod.set_default_bounds(cfg.hist_buckets)
    tracer = trace_mod.Tracer(
        worker_index=worker_index if worker_index is not None else 0,
        sample_every=cfg.trace_sample,
    )
    trace_mod.install(tracer)
    jrn = None
    if cfg.journal_path:
        # one writer per file: train fleets write .w<i>, serve scoring
        # processes .s<i>, the lifecycle controller .l<i> — the reader
        # merges the set by (ts, writer, seq)
        suffix = {"serve": "s", "lifecycle": "l"}.get(plane, "w")
        path = (
            cfg.journal_path
            if worker_index is None
            else f"{cfg.journal_path}.{suffix}{worker_index}"
        )
        jrn = journal_mod.Journal(
            path,
            max_bytes=cfg.journal_max_bytes,
            max_files=cfg.journal_max_files,
            plane=plane,
            worker=worker_index,
            job=job,
        )
        journal_mod.install(jrn)
    # the SLO watchdog installs whenever obs is on: with no slo-* target
    # configured it still feeds the stpu_slo_* gauges and the anomaly
    # detector — consumers (ScoringServer, Trainer) pick it up via
    # slo.active() the same way the trainer picks up the tracer
    slo_mod.install(slo_mod.from_config(cfg, plane=plane,
                                        worker=worker_index))
    # device/compiler leg (PR 10): the compile flight recorder and the
    # device-memory accountant install beside the watchdog — seams pick
    # them up via compile.active()/memory.active() exactly like the
    # tracer; the profiler trigger polls only when a journal exists (the
    # journal base is the operator's rendezvous point)
    analysis = getattr(cfg, "compile_analysis", "auto")
    if analysis == "auto":
        # full memory_analysis costs a second backend compile: fine on
        # the train plane (compiles are rare and off any request path),
        # not on serve, where a request-path compile runs under the
        # compute lock on the dispatch thread
        analysis = "cost" if plane == "serve" else "full"
    compile_mod.install(compile_mod.CompileRecorder(
        plane=plane, worker=worker_index,
        analysis=analysis,
        storm_window_s=cfg.slo_window_s,
        storm_threshold=getattr(cfg, "compile_storm", 8),
    ))
    memory_mod.install(memory_mod.MemoryAccountant(
        plane=plane, worker=worker_index))
    # fleet leg (PR 11): the coordinator feeds it from workers' epoch
    # reports (fleet.active() in report_epoch); on planes that never see
    # fleet traffic it idles at zero cost like the other legs
    fleet_mod.install(fleet_mod.FleetMonitor(
        skew_threshold=getattr(cfg, "fleet_skew_threshold", 1.5),
        hysteresis=cfg.slo_hysteresis,
        plane=plane,
    ))
    # data leg (PR 12): the drift monitor (serve batchers feed it per
    # dispatch, model stores register bundle baselines, the SLO tick
    # evaluates it) and the train-side sketch (ingest taps feed it
    # under the trace-sample discipline; export ships its snapshot as
    # the bundle's feature_stats.json)
    datastats_mod.install(datastats_mod.DataDriftMonitor(
        threshold=getattr(cfg, "data_drift_threshold", 1.0),
        hysteresis=cfg.slo_hysteresis,
        window_s=cfg.slo_window_s,
        plane=plane,
        worker=worker_index,
    ))
    datastats_mod.install_train(datastats_mod.TrainDataSketch(
        sample_every=cfg.trace_sample))
    # cost leg (PR 13): the device-time ledger every dispatch seam feeds
    # (batcher _dispatch_one, Trainer._obs_epoch) — registered as a
    # rollup counter source so per-tenant device-seconds survive journal
    # rotation
    acct = cost_mod.install(cost_mod.CostAccountant(
        plane=plane, worker=worker_index))
    rollup_mod.register_source("cost", acct.counters)
    # rollup compactor (PR 13): one per journal WRITER, tapping its emit
    # path and appending per-window aggregates to the rotation-exempt
    # <journal>.rollup.jsonl sidecar; the journal's close hook does the
    # final flush so a drained fleet's sidecar is complete
    if jrn is not None and getattr(cfg, "rollup", True):
        comp = rollup_mod.install(rollup_mod.RollupCompactor(
            rollup_mod.rollup_path(jrn.path),
            window_s=getattr(cfg, "rollup_window_s", 60.0),
            plane=plane, worker=worker_index, job=job,
        ))
        jrn.set_tap(comp.note_event)
        jrn.on_close(comp.close)
    else:
        rollup_mod.uninstall()
    # cross-run regression watchdog: live windowed digests vs the pinned
    # baseline rollup — both the target and the baseline must be set,
    # and an unreadable baseline degrades to a logged warning, never a
    # refused job (observability must not take down what it observes)
    baseline_path = getattr(cfg, "baseline_path", "")
    threshold = getattr(cfg, "slo_regression", 0.0)
    rollup_mod.uninstall_regression()
    if baseline_path and threshold > 1:
        baseline = rollup_mod.load_baseline(baseline_path)
        if baseline is None or not baseline.get("digests"):
            rollup_mod.log.warning(
                "obs-baseline %r has no readable rollup digests; "
                "regression watchdog disabled", baseline_path)
        else:
            rollup_mod.install_regression(rollup_mod.RegressionWatchdog(
                baseline, threshold=threshold,
                hysteresis=cfg.slo_hysteresis,
                plane=plane, worker=worker_index,
            ))
    profile_mod.configure(cfg.journal_path or None, plane=plane,
                          worker=worker_index)
    return tracer, jrn
