"""Data-plane observability: streaming per-feature sketches and
train/serve skew detection.

The obs plane watches time (PR 4), requests (PR 7), devices (PR 10),
and the fleet (PR 11) — this leg watches the *data*.  Shifu's whole
pipeline is built around per-column statistics (``ColumnConfig``
mean/stdDev feeding ZSCALE normalization), and a tabular serving fleet
dies silently from feature drift and train/serve skew long before any
latency SLO fires: the model keeps answering, the scores are just
quietly wrong.  The tf.data lesson (arxiv 2101.12127) applied to the
data itself — instrument the pipeline per element, compare live against
the training distribution, and turn the comparison into journaled state
transitions a supervisor can act on.

Three layers, stdlib + numpy, bounded memory, off-by-default-cheap
(every tap is one ``is None`` check when obs is off):

- :class:`DataSketch` — one streaming sketch over a feature matrix:
  per-feature count, mean/std (Welford, merged batch-at-a-time with
  Chan's parallel update — vectorized, no per-value Python), min/max,
  NaN ("missing") and ±inf rates, plus P² quantile estimators
  (:class:`~shifu_tensorflow_tpu.obs.slo.P2Quantile`) fed from a
  bounded per-batch row subsample so quantile cost cannot scale with
  batch size × width.
- :class:`WindowedDataSketch` — the serve-side live window: a ring of
  time cells (the :class:`~shifu_tensorflow_tpu.obs.slo.WindowedDigest`
  discipline), each holding one DataSketch; cells expire by ring reuse
  and :func:`merge_snapshots` combines the live cells count-weighted.
  An empty window is signal ABSENT, never a drift of zero.
- :class:`DataDriftMonitor` — per-model :class:`SkewDetector` comparing
  the live windowed sketch against the model's *baseline* (the training
  sketch shipped in the bundle as ``feature_stats.json``, verified by
  the PR-3 manifest chain like any artifact).  Per feature it computes
  a PSI-style normalized displacement score — mean shift and std shift
  in units of the baseline's (robust) spread, max quantile
  displacement, missing/inf-rate deltas — and runs a hysteretic state
  machine per (model, feature): ``data_drift`` journaled with the
  model, feature index/column, offending statistic and score;
  ``data_drift_clear`` when the live window returns to the baseline.
  ``stpu_data_*`` gauges ride every ``/metrics`` surface
  (obs.device_obs_text) and the fleet-wide max score feeds the
  ``shifu.tpu.slo-data-drift`` watchdog target.

Taps: the ingest pipeline feeds the TRAIN sketch at batch formation
(``data/pipeline.blocks_to_batches``, train-emit streams only, sampled
under the ``shifu.tpu.obs-trace-sample`` discipline); the in-memory and
device-resident fit paths fold their dataset once per fit.  The serve
batcher feeds the LIVE sketch at its pack stage — once per coalesced
dispatch, pre-padding, so ladder padding can never read as drift.  At
export the train snapshot lands in the bundle; at admission the
ModelStore registers it as the baseline.  The whole story reconstructs
jax-free from a dead fleet's journals + bundle files (``obs data``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Sequence

import numpy as np

from shifu_tensorflow_tpu.obs.registry import (
    escape_label_suffix as _esc,  # one escape across every obs leg
)
from shifu_tensorflow_tpu.obs.slo import P2Quantile
from shifu_tensorflow_tpu.utils import logs

log = logs.get("obs.datastats")

__all__ = [
    "DataSketch",
    "WindowedDataSketch",
    "merge_snapshots",
    "drift_components",
    "SkewDetector",
    "DataDriftMonitor",
    "TrainDataSketch",
    "install",
    "uninstall",
    "active",
    "install_train",
    "uninstall_train",
    "train_active",
    "baseline_from_journal",
]

_mono = time.monotonic

#: quantiles every sketch tracks: the median for center displacement,
#: the 5/95 tails for shape — (p95 - p5)/3.29 doubles as a robust std
#: estimate for the drift score's scale when the baseline std is
#: degenerate (constant or outlier-inflated features)
QUANTILES = (0.05, 0.5, 0.95)

#: per-tap cap on P² updates (P2Quantile.add is ~3µs of Python, and a
#: tap fires on the serve pack thread / per sampled ingest block): rows
#: fed per add_batch = budget // (width × quantiles), floored at 1 — so
#: the per-tap Python cost is ~budget × 3µs regardless of width.  The
#: drift score discounts the resulting sampling noise by the cumulative
#: fed-row count (``qrows``), so a small per-tap feed costs resolution,
#: never correctness — over a window/epoch the rows accumulate.
QUANTILE_BUDGET = 96

#: per-tap cap on rows folded into the vectorized moment/rate stats: a
#: 64k-row ingest block would otherwise cost ~10 numpy passes over 2M
#: elements per tap.  Rows beyond the cap are evenly strided out; all
#: rates stay unbiased, and ``rows`` counts what was actually folded.
#: 2048/tap × the dozens of taps per epoch/window is tens of thousands
#: of folded rows — sampling error far below the drift threshold.
MOMENT_ROW_CAP = 2048

#: sampling-noise allowance subtracted from the quantile drift
#: component: a p05/p95 estimate from n fed rows wobbles ~O(1/√n)
#: baseline-sigmas even with NO drift, and alarming on that would be
#: alarming on the estimator, not the data
QUANTILE_NOISE_K = 3.0

#: a live window below this many rows never evaluates: a handful of
#: requests is a sample, not a distribution, and drift alarms off six
#: rows would train operators to ignore the event
MIN_EVAL_ROWS = 32

#: extra weight on the missing/inf RATE deltas in the drift score —
#: rates live in [0, 1] while the moment shifts are in baseline-sigmas,
#: so a 25-point missing-rate change scores 1.0 (the default threshold)
RATE_WEIGHT = 4.0




def _round_list(vals, nd: int = 5) -> list:
    out = []
    for v in vals:
        v = float(v)
        out.append(round(v, nd) if math.isfinite(v) else None)
    return out


class DataSketch:
    """Streaming per-feature statistics over ``add_batch(x)`` calls
    (``x`` is ``(rows, features)``).  All moment/extreme/rate stats are
    exact over every row seen (vectorized numpy, float64 accumulators);
    the P² quantiles see a bounded evenly-strided row subsample per
    batch.  Thread-safe; ``snapshot()`` is JSON-ready."""

    def __init__(self, num_features: int | None = None,
                 quantiles: tuple[float, ...] = QUANTILES,
                 quantile_budget: int = QUANTILE_BUDGET):
        self.quantiles = tuple(quantiles)
        self.quantile_budget = max(1, int(quantile_budget))
        self._lock = threading.Lock()
        self.rows = 0
        self.num_features = 0
        self._count = self._missing = self._inf = None
        self._mean = self._m2 = self._min = self._max = None
        self._p2: list[dict[float, P2Quantile]] = []
        if num_features:
            self._alloc(int(num_features))

    def _alloc(self, f: int) -> None:
        self.num_features = f
        self.rows = 0
        self.qrows = 0
        self._count = np.zeros(f, np.int64)
        self._missing = np.zeros(f, np.int64)
        self._inf = np.zeros(f, np.int64)
        self._mean = np.zeros(f, np.float64)
        self._m2 = np.zeros(f, np.float64)
        self._min = np.full(f, np.inf)
        self._max = np.full(f, -np.inf)
        self._p2 = [{q: P2Quantile(q) for q in self.quantiles}
                    for _ in range(f)]

    def add_batch(self, x) -> None:
        x = np.asarray(x)
        if x.ndim != 2 or x.shape[0] == 0:
            return
        if x.shape[0] > MOMENT_ROW_CAP:
            # bounded per-tap cost: evenly strided row subsample — every
            # rate stays unbiased, `rows` counts what was folded.  The
            # contiguous copy matters: ten numpy passes over a strided
            # view of a 64k-row block cost ~3× the one gather.
            x = np.ascontiguousarray(x[:: -(-x.shape[0] // MOMENT_ROW_CAP)])
        n, f = x.shape
        with self._lock:
            if self._count is None or f != self.num_features:
                # width change (a new trainer in the same process, a
                # reloaded model): restart rather than mix two schemas
                self._alloc(f)
            xf = x.astype(np.float64, copy=False)
            finite = np.isfinite(xf)
            nan = np.isnan(xf)
            self.rows += n
            cnt = finite.sum(axis=0)
            nnan = nan.sum(axis=0)
            self._missing += nnan
            self._inf += n - cnt - nnan
            vals = np.where(finite, xf, 0.0)
            has = cnt > 0
            bsum = vals.sum(axis=0)
            bmean = np.divide(bsum, cnt, out=np.zeros_like(bsum),
                              where=has)
            bm2 = (np.where(finite, xf - bmean, 0.0) ** 2).sum(axis=0)
            # Chan's parallel combine of (count, mean, M2) pairs
            tot = self._count + cnt
            safe = np.maximum(tot, 1)
            delta = bmean - self._mean
            self._mean = np.where(
                has, self._mean + delta * (cnt / safe), self._mean)
            self._m2 = np.where(
                has, self._m2 + bm2 + delta ** 2 * (self._count * cnt / safe),
                self._m2)
            self._count = tot
            self._min = np.minimum(
                self._min, np.where(finite, xf, np.inf).min(axis=0))
            self._max = np.maximum(
                self._max, np.where(finite, xf, -np.inf).max(axis=0))
            # bounded quantile feed: a handful of evenly-strided rows
            # per tap (P2Quantile.add is scalar Python — the budget
            # counts CALLS, width × quantiles of them per row; the
            # drift score's √qrows discount absorbs the small feed)
            k = max(1, self.quantile_budget
                    // max(1, f * len(self.quantiles)))
            stride = max(1, n // k)
            for i in range(0, n, stride):
                row = xf[i]
                ok = finite[i]
                self.qrows += 1
                for j in range(f):
                    if ok[j]:
                        for p2 in self._p2[j].values():
                            p2.add(row[j])

    def _q_value(self, j: int, q: float) -> float:
        p2 = self._p2[j][q]
        v = p2.value() if p2.count else None
        return float("nan") if v is None else v

    def snapshot(self) -> dict | None:
        """JSON-ready struct-of-arrays snapshot, or None before any
        data.  ``count`` is finite observations per feature; min/max are
        None for features that never saw a finite value."""
        with self._lock:
            if self._count is None or self.rows == 0:
                return None
            cnt = self._count
            safe = np.maximum(cnt, 1)
            var = np.where(cnt > 1, self._m2 / safe, 0.0)
            seen = np.maximum(cnt + self._missing + self._inf, 1)
            snap: dict[str, Any] = {
                "rows": int(self.rows),
                "qrows": int(self.qrows),
                "num_features": self.num_features,
                "count": [int(c) for c in cnt],
                "missing": [int(m) for m in self._missing],
                "inf": [int(m) for m in self._inf],
                "mean": _round_list(self._mean),
                "std": _round_list(np.sqrt(np.maximum(var, 0.0))),
                "min": _round_list(self._min),
                "max": _round_list(self._max),
                "missing_rate": _round_list(self._missing / seen, 6),
                "inf_rate": _round_list(self._inf / seen, 6),
                "quantiles": {
                    str(q): _round_list(
                        [self._q_value(j, q)
                         for j in range(self.num_features)])
                    for q in self.quantiles
                },
            }
            return snap


def merge_snapshots(snaps: Sequence[dict]) -> dict | None:
    """Count-weighted combine of :meth:`DataSketch.snapshot` dicts with
    a common width (window cells, fleet workers): counts sum, means and
    M2 merge via Chan, min/max extremize, quantiles average count-
    weighted (the WindowedDigest estimate — exact when the parts saw
    similar distributions, which is precisely the no-drift case).

    Mixed widths cannot merge; the LAST snapshot's width wins, so
    callers must pass oldest-first when their parts can disagree (the
    windowed ring sorts cells by start; the journal reconstruction
    sorts by event timestamp)."""
    snaps = [s for s in snaps if s and s.get("rows")]
    if not snaps:
        return None
    widths = {s["num_features"] for s in snaps}
    if len(widths) > 1:
        w = snaps[-1]["num_features"]
        snaps = [s for s in snaps if s["num_features"] == w]
    f = snaps[0]["num_features"]
    qrows = sum(int(s.get("qrows", 0)) for s in snaps)
    count = np.zeros(f, np.float64)
    missing = np.zeros(f, np.float64)
    inf = np.zeros(f, np.float64)
    mean = np.zeros(f, np.float64)
    m2 = np.zeros(f, np.float64)
    mn = np.full(f, np.inf)
    mx = np.full(f, -np.inf)
    rows = 0
    qkeys = list(snaps[0].get("quantiles", {}))
    qnum = {q: np.zeros(f, np.float64) for q in qkeys}
    qden = {q: np.zeros(f, np.float64) for q in qkeys}
    for s in snaps:
        rows += int(s["rows"])
        c = np.asarray(s["count"], np.float64)
        missing += np.asarray(s["missing"], np.float64)
        inf += np.asarray(s["inf"], np.float64)
        sm = np.array([v if v is not None else 0.0 for v in s["mean"]])
        sd = np.array([v if v is not None else 0.0 for v in s["std"]])
        tot = count + c
        safe = np.maximum(tot, 1)
        delta = sm - mean
        has = c > 0
        mean = np.where(has, mean + delta * (c / safe), mean)
        m2 = np.where(has, m2 + sd ** 2 * c + delta ** 2 * (count * c / safe),
                      m2)
        count = tot
        mn = np.minimum(mn, np.array(
            [v if v is not None else np.inf for v in s["min"]]))
        mx = np.maximum(mx, np.array(
            [v if v is not None else -np.inf for v in s["max"]]))
        for q in qkeys:
            vals = s.get("quantiles", {}).get(q)
            if vals is None:
                continue
            v = np.array([x if x is not None else np.nan for x in vals])
            ok = np.isfinite(v) & has
            qnum[q] += np.where(ok, v * c, 0.0)
            qden[q] += np.where(ok, c, 0.0)
    safe = np.maximum(count, 1)
    seen = np.maximum(count + missing + inf, 1)
    return {
        "rows": rows,
        "qrows": qrows,
        "num_features": f,
        "count": [int(c) for c in count],
        "missing": [int(m) for m in missing],
        "inf": [int(m) for m in inf],
        "mean": _round_list(mean),
        "std": _round_list(np.sqrt(np.maximum(m2 / safe, 0.0))),
        "min": _round_list(mn),
        "max": _round_list(mx),
        "missing_rate": _round_list(missing / seen, 6),
        "inf_rate": _round_list(inf / seen, 6),
        "quantiles": {
            q: _round_list(np.divide(qnum[q], qden[q],
                                     out=np.full(f, np.nan),
                                     where=qden[q] > 0))
            for q in qkeys
        },
    }


class WindowedDataSketch:
    """Sliding live window as a ring of time-cell DataSketches (the
    obs/slo.py WindowedDigest discipline): a cell whose slot comes
    around again is reset, so rows older than the window can never
    contribute.  ``snapshot`` merges live cells; None when empty.

    ``cell_row_cap`` bounds the work per time cell: once a cell has
    folded that many rows, further taps are ONE attribute read until
    the ring rolls — total sketch work per window is capped at
    buckets × cap rows no matter the request rate, which is what lets
    the serve pack thread call this per dispatch unconditionally.
    (Statistics come from the cell's first ``cap`` rows — a time-
    leading sample within one short bucket, fine for drift.)"""

    def __init__(self, window_s: float = 60.0, buckets: int = 4,
                 quantile_budget: int = QUANTILE_BUDGET,
                 cell_row_cap: int = 4096):
        self.window_s = float(window_s)
        self.buckets = max(2, int(buckets))
        self.bucket_s = self.window_s / self.buckets
        self.quantile_budget = quantile_budget
        self.cell_row_cap = int(cell_row_cap)
        self._cells: list[list] = [None] * self.buckets  # [start, sketch]
        self._lock = threading.Lock()

    def add(self, x, now: float | None = None) -> None:
        now = _mono() if now is None else now
        start = (now // self.bucket_s) * self.bucket_s
        idx = int(now // self.bucket_s) % self.buckets
        with self._lock:
            cell = self._cells[idx]
            if cell is None or cell[0] != start:
                cell = [start, DataSketch(
                    quantile_budget=self.quantile_budget)]
                self._cells[idx] = cell
            sketch = cell[1]
        if self.cell_row_cap and sketch.rows >= self.cell_row_cap:
            return
        sketch.add_batch(x)

    def rows(self, now: float | None = None) -> int:
        now = _mono() if now is None else now
        with self._lock:
            return sum(c[1].rows for c in self._cells
                       if c is not None and now - c[0] < self.window_s)

    def snapshot(self, now: float | None = None) -> dict | None:
        now = _mono() if now is None else now
        with self._lock:
            # oldest-first: merge_snapshots keeps the LAST snapshot's
            # width on a mixed-width window (a reload that changed the
            # model's feature count), and "last" must mean newest — the
            # ring's index order is arbitrary
            live = sorted(
                (c for c in self._cells
                 if c is not None and now - c[0] < self.window_s),
                key=lambda c: c[0])
            live = [c[1] for c in live]
        snaps = [s for s in (sk.snapshot() for sk in live) if s]
        return merge_snapshots(snaps) if snaps else None


# ---- drift scoring ----------------------------------------------------------

def _feature_scale(base: dict, j: int) -> float:
    """The baseline's per-feature spread, robustly: max of its std and
    the (p95 - p5)/3.29 robust std (a heavy-tailed baseline would
    otherwise inflate the scale and hide a real shift; a clipped one
    would deflate it and alarm on noise).  A constant feature falls
    back to 1% of |mean| so ANY movement off the constant scores
    large — which is what a constant training column drifting at serve
    should do."""
    std = base["std"][j] or 0.0
    q = base.get("quantiles", {})
    p5 = (q.get("0.05") or [None] * (j + 1))[j]
    p95 = (q.get("0.95") or [None] * (j + 1))[j]
    robust = 0.0
    if p5 is not None and p95 is not None:
        robust = (p95 - p5) / 3.29
    scale = max(std, robust)
    if scale <= 0.0:
        scale = 0.01 * abs(base["mean"][j] or 0.0)
    return max(scale, 1e-9)


def drift_components(base: dict, live: dict, j: int) -> dict[str, float]:
    """Per-feature drift components, each dimensionless and ~1.0 at
    "clearly drifted": mean/std displacement in baseline-scale units,
    max quantile displacement, and weighted missing/inf rate deltas.
    The max of these is the feature's drift score and the argmax names
    the offending statistic in the journaled event."""
    scale = _feature_scale(base, j)

    def g(snap, key):
        v = snap[key][j]
        return float(v) if v is not None else 0.0

    comps = {
        "mean": abs(g(live, "mean") - g(base, "mean")) / scale,
        "std": abs(g(live, "std") - g(base, "std")) / scale,
        "missing_rate": RATE_WEIGHT * abs(
            g(live, "missing_rate") - g(base, "missing_rate")),
        "inf_rate": RATE_WEIGHT * abs(
            g(live, "inf_rate") - g(base, "inf_rate")),
    }
    qshift = 0.0
    bq, lq = base.get("quantiles", {}), live.get("quantiles", {})
    for q in bq:
        bv = (bq.get(q) or [])
        lv = (lq.get(q) or [])
        if j < len(bv) and j < len(lv) and bv[j] is not None \
                and lv[j] is not None:
            qshift = max(qshift, abs(lv[j] - bv[j]) / scale)
    # discount the estimators' own sampling noise: the quantile feed is
    # a bounded row subsample, and a tail estimate from n rows wobbles
    # ~O(1/√n) sigmas drift-free — without this, a quiet low-traffic
    # window would alarm on estimator variance
    n = min(int(base.get("qrows", 0) or 0) or 10 ** 9,
            int(live.get("qrows", 0) or 0) or 10 ** 9)
    comps["quantile"] = max(
        0.0, qshift - QUANTILE_NOISE_K / math.sqrt(max(n, 1)))
    return comps


class _FeatureState:
    __slots__ = ("bad", "good", "breached", "since", "stat", "score")

    def __init__(self):
        self.bad = 0
        self.good = 0
        self.breached = False
        self.since: float | None = None
        self.stat = ""
        self.score = 0.0


class SkewDetector:
    """One model's live-vs-baseline comparison: a windowed live sketch,
    the bundle-shipped baseline, and a hysteretic per-feature state
    machine.  ``evaluate`` returns the events to journal (the monitor
    owns journaling so the plane/worker stamps stay in one place)."""

    def __init__(self, model: str, baseline: dict | None, *,
                 columns: Sequence[int] | None = None,
                 threshold: float = 1.0, hysteresis: int = 2,
                 window_s: float = 60.0, min_rows: int = MIN_EVAL_ROWS):
        self.model = model
        self.baseline = baseline if baseline and baseline.get("rows") else None
        self.columns = list(columns) if columns else None
        self.threshold = float(threshold)
        self.hysteresis = max(1, int(hysteresis))
        self.min_rows = int(min_rows)
        self.live = WindowedDataSketch(window_s=window_s)
        self._state: dict[int, _FeatureState] = {}
        self.last_score = 0.0
        self.last_live: dict | None = None

    def column_of(self, j: int):
        if self.columns and j < len(self.columns):
            return self.columns[j]
        return None

    def observe(self, x, now: float | None = None) -> None:
        self.live.add(x, now=now)

    def evaluate(self, now: float | None = None) -> list[dict]:
        now = _mono() if now is None else now
        live = self.live.snapshot(now=now)
        self.last_live = live
        events: list[dict] = []
        base = self.baseline
        if base is None:
            self.last_score = 0.0
            return events
        # an EMPTY window (live None) still ticks the state machine as
        # clean: a tenant whose traffic stopped entirely must clear its
        # open drift, not hold it forever (the slo.py empty-window rule)
        evaluable = (live is not None
                     and live["rows"] >= self.min_rows
                     and live["num_features"] == base["num_features"])
        max_score = 0.0
        for j in range(base["num_features"]):
            st = self._state.setdefault(j, _FeatureState())
            if not evaluable:
                # signal absent: never starts a breach, counts clean
                # (the slo.py empty-window rule — a tenant whose traffic
                # stopped entirely must still clear)
                breaching = False
                st.score = 0.0
            else:
                comps = drift_components(base, live, j)
                st.stat, st.score = max(comps.items(), key=lambda kv: kv[1])
                breaching = st.score >= self.threshold
                max_score = max(max_score, st.score)
            if breaching:
                st.bad += 1
                st.good = 0
                if not st.breached and st.bad >= self.hysteresis:
                    st.breached = True
                    st.since = now
                    ev = {"event": "data_drift", "model": self.model,
                          "feature": j, "stat": st.stat,
                          "score": round(st.score, 4),
                          "threshold": self.threshold,
                          "live_rows": live["rows"],
                          "value": live["mean"][j],
                          "baseline": base["mean"][j]}
                    col = self.column_of(j)
                    if col is not None:
                        ev["column"] = col
                    events.append(ev)
            else:
                st.good += 1
                st.bad = 0
                if st.breached and st.good >= self.hysteresis:
                    st.breached = False
                    ev = {"event": "data_drift_clear", "model": self.model,
                          "feature": j, "stat": st.stat,
                          "score": round(st.score, 4),
                          "drift_s": round(now - (st.since or now), 3)}
                    col = self.column_of(j)
                    if col is not None:
                        ev["column"] = col
                    st.since = None
                    events.append(ev)
        self.last_score = max_score
        return events

    def drifting(self) -> int:
        return sum(1 for st in self._state.values() if st.breached)


class DataDriftMonitor:
    """Process-wide registry of per-model skew detectors (the
    install/active pattern every obs leg uses).  The serve batcher's
    pack stage calls ``observe`` per coalesced dispatch; the serve SLO
    tick calls ``evaluate`` — which journals ``data_drift``/
    ``data_drift_clear`` transitions, refreshes the ``stpu_data_*``
    gauges, journals one windowed ``data_stats`` snapshot per model per
    window (the dead-fleet record ``obs data`` reads), and feeds the
    fleet-wide max score to the ``slo-data-drift`` watchdog target."""

    def __init__(self, *, threshold: float = 1.0, hysteresis: int = 2,
                 window_s: float = 60.0, plane: str = "serve",
                 worker: int | None = None,
                 min_rows: int = MIN_EVAL_ROWS):
        from shifu_tensorflow_tpu.obs.registry import MetricsRegistry

        self.threshold = float(threshold)
        self.hysteresis = int(hysteresis)
        self.window_s = float(window_s)
        self.plane = plane
        self.worker = worker
        self.min_rows = int(min_rows)
        self.registry = MetricsRegistry()
        self._detectors: dict[str, SkewDetector] = {}
        self._last_stats_emit: dict[str, float] = {}
        self._lock = threading.Lock()
        self._eval_lock = threading.Lock()
        self._warned = False

    # ---- registration ----
    def _close_open_breaches(self, det: "SkewDetector | None",
                             reason: str) -> None:
        """Journal ``data_drift_clear`` for every feature a discarded
        detector left BREACHED: a reload (new baseline = new contract)
        or an eviction ends the excursion, and leaving it open in the
        journal forever would render as STILL DRIFTING in `obs data`
        long after the condition stopped existing."""
        from shifu_tensorflow_tpu.obs import journal as obs_journal

        if det is None:
            return
        now = _mono()
        for j, st in det._state.items():
            if not st.breached:
                continue
            fields = {"model": det.model, "feature": j, "stat": st.stat,
                      "score": round(st.score, 4),
                      "drift_s": round(now - (st.since or now), 3),
                      "reason": reason}
            col = det.column_of(j)
            if col is not None:
                fields["column"] = col
            obs_journal.emit("data_drift_clear", plane=self.plane,
                             worker=self.worker, **fields)

    def register(self, model: str, baseline: dict | None, *,
                 columns: Sequence[int] | None = None) -> SkewDetector:
        """(Re-)register a model's detector.  ``baseline`` is the
        ``stats`` dict out of the bundle's ``feature_stats.json`` (None
        = no shipped baseline: the live sketch still collects — visible
        in ``obs data`` and the gauges — but nothing can breach).
        Re-registration (hot reload) keeps the live window and resets
        the baseline + state machines: the new bundle's distribution is
        a new contract — any open drift clears (journaled) with the old
        baseline it was measured against."""
        with self._lock:
            old = self._detectors.get(model)
            det = SkewDetector(
                model, baseline, columns=columns,
                threshold=self.threshold, hysteresis=self.hysteresis,
                window_s=self.window_s, min_rows=self.min_rows)
            if old is not None:
                det.live = old.live  # keep the live window across reloads
            self._detectors[model] = det
        self._close_open_breaches(old, reason="reload")
        return det

    def unregister(self, model: str) -> None:
        """Drop a model (eviction/close): its gauges leave the scrape
        with it — a frozen drift score for an unrouted tenant would
        mislead exactly the autoscaler these gauges feed — and any open
        drift excursion clears in the journal (reason=evict)."""
        with self._eval_lock:
            with self._lock:
                old = self._detectors.pop(model, None)
                self._last_stats_emit.pop(model, None)
            self._close_open_breaches(old, reason="evict")
            esc = _esc(model)
            for g in ("data_drift_score_", "data_drifting_features_",
                      "data_live_rows_", "data_baseline_rows_"):
                self.registry.remove_gauge(g + esc)

    def detector(self, model: str) -> SkewDetector | None:
        with self._lock:
            return self._detectors.get(model)

    # ---- hot path ----
    def observe(self, model: str, x) -> None:
        """Feed one pre-padding feature matrix into ``model``'s live
        window (auto-registering a baseline-less detector for an
        unknown name).  Never raises — a sketch bug must not take down
        the dispatch path it instruments."""
        try:
            det = self._detectors.get(model)
            if det is None:
                det = self.register(model, None)
            det.observe(x)
        except Exception as e:
            if not self._warned:
                self._warned = True
                log.warning("data sketch observe failed (disabled for "
                            "this message): %s: %s", type(e).__name__, e)

    # ---- slow path ----
    def evaluate(self, now: float | None = None, **ctx: Any) -> list[dict]:
        """One evaluation tick over every registered model (the serve
        SLO loop's cadence).  Returns the journaled events."""
        from shifu_tensorflow_tpu.obs import journal as obs_journal
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        with self._lock:
            detectors = list(self._detectors.items())
        events: list[dict] = []
        fleet_max = None
        with self._eval_lock:
            for model, det in detectors:
                events.extend(det.evaluate(now=now))
                esc = _esc(model)
                live = det.last_live
                self.registry.set_gauge(f"data_drift_score_{esc}",
                                        round(det.last_score, 4))
                self.registry.set_gauge(f"data_drifting_features_{esc}",
                                        det.drifting())
                self.registry.set_gauge(f"data_live_rows_{esc}",
                                        live["rows"] if live else 0)
                self.registry.set_gauge(
                    f"data_baseline_rows_{esc}",
                    det.baseline["rows"] if det.baseline else 0)
                if live is not None and det.baseline is not None:
                    fleet_max = max(fleet_max or 0.0, det.last_score)
                # one windowed snapshot per model per window: the
                # journal records state, not tick noise — and `obs
                # data` renders the live table from exactly these
                mono = _mono() if now is None else now
                last = self._last_stats_emit.get(model, 0.0)
                if live is not None and mono - last >= self.window_s:
                    self._last_stats_emit[model] = mono
                    obs_journal.emit(
                        "data_stats", plane=self.plane, worker=self.worker,
                        model=model, stats=live,
                        drift_score=round(det.last_score, 4),
                        drifting=det.drifting(), **ctx)
        for ev in events:
            fields = {k: v for k, v in ev.items() if k != "event"}
            obs_journal.emit(ev["event"], plane=self.plane,
                             worker=self.worker, **fields, **ctx)
        wd = obs_slo.active()
        if wd is not None and fleet_max is not None:
            # window MAX across models: one drifted tenant IS the
            # breach — averaging it against healthy peers would hide it
            wd.observe("data_drift_score", fleet_max)
        return events

    def render_prometheus(self) -> str:
        """``stpu_data_*`` gauges — appended to every scrape surface by
        ``obs.device_obs_text``."""
        return self.registry.render_prometheus("stpu_")


class TrainDataSketch:
    """The training-side accumulator: one process-wide DataSketch fed
    from the ingest tap (sampled every Nth block under the trace-sample
    discipline) and, for in-memory fits, one whole-dataset fold per
    ``fit``.  Its snapshot is the baseline the export ships as
    ``feature_stats.json``.

    Generation semantics: every trainer fit path brackets itself with
    ``begin_fit``/``end_fit``.  Concurrent fits (a thread-launcher
    fleet's workers — one job, one data distribution) SHARE the sketch;
    a fit starting after every previous fit ended is a NEW training
    (same process, possibly a different dataset of the same width) and
    RESETS it — without this, the second training's export would ship a
    baseline blended with the first one's data.

    The block tap is ASYNCHRONOUS: ``add_block`` copies a bounded row
    subsample (microseconds) and a single background folder thread runs
    the actual fold — the GIL-bound sketch work must not sit inside a
    worker's streaming step path, where it would read as per-rank step
    skew to the very fleet monitor the obs plane runs (measured: the
    in-line fold intermittently tripped the straggler drill's no-fault
    control arm on a 2-core host).  The queue is bounded; a producer
    outpacing the folder drops samples (counted), never blocks.
    ``snapshot`` flushes the queue first, so exports see every fed
    block."""

    def __init__(self, sample_every: int = 1):
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._active_fits: set[int] = set()
        self._had_fits = False
        self._pending: list = []
        self._cond = threading.Condition(self._lock)
        self._folding = 0
        self.dropped = 0
        self._thread: threading.Thread | None = None
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.sketch = DataSketch()
        self._n = 0
        self._pending.clear()
        # id -> weakref of folded dataset arrays: the weakref guards the
        # CPython id-reuse hazard (a freed array's id can be handed to a
        # later, different array — a bare id set would silently skip it)
        self._datasets: dict[int, Any] = {}

    def begin_fit(self, owner: int) -> None:
        with self._lock:
            if not self._active_fits and self._had_fits:
                self._reset_locked()
            self._had_fits = True
            self._active_fits.add(owner)

    def end_fit(self, owner: int) -> None:
        with self._lock:
            self._active_fits.discard(owner)

    def add_block(self, x) -> None:
        """Ingest tap: one pre-batching feature block (padding-free by
        construction), sampled.  Cheap by contract — a strided bounded
        copy plus a queue append; the fold happens on the folder
        thread."""
        self._n += 1
        if self._n % self.sample_every:
            return
        x = np.asarray(x)
        if x.ndim != 2 or x.shape[0] == 0:
            return
        if x.shape[0] > MOMENT_ROW_CAP:
            x = x[:: -(-x.shape[0] // MOMENT_ROW_CAP)]
        # copy: the pipeline recycles/mutates block buffers, and the
        # fold happens later on another thread
        sample = np.array(x, copy=True)
        with self._lock:
            if len(self._pending) >= 16:
                self.dropped += 1
                return
            self._pending.append(sample)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._fold_loop, name="stpu-data-sketch",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()

    def _fold_loop(self) -> None:
        while True:
            with self._cond:
                while not self._pending:
                    # park; a long-idle folder thread just sleeps on the
                    # condition (daemon — dies with the process)
                    self._cond.wait()
                sample = self._pending.pop(0)
                sketch = self.sketch
                self._folding += 1
            try:
                sketch.add_batch(sample)
            except Exception:  # the folder must never die mute mid-job
                pass
            finally:
                with self._cond:
                    self._folding -= 1
                    self._cond.notify_all()

    def _flush(self, timeout_s: float = 5.0) -> None:
        deadline = _mono() + timeout_s
        with self._cond:
            while (self._pending or self._folding) and _mono() < deadline:
                self._cond.wait(timeout=0.05)

    def add_dataset(self, x) -> None:
        """In-memory fit tap: fold the whole training matrix once per
        distinct array (epochs re-iterate the same rows — re-folding
        them every epoch would just weight the identical distribution
        by the epoch count).  Chunked, so the per-call quantile-feed
        cap applies per chunk and a one-shot fold still gives the P²
        estimators a real sample, not budget-many rows of a million."""
        import weakref

        x = np.asarray(x)
        key = id(x)
        with self._lock:
            ref = self._datasets.get(key)
            if ref is not None and ref() is x:
                return
            try:
                self._datasets[key] = weakref.ref(x)
            except TypeError:  # non-weakrefable base: fold every call
                self._datasets.pop(key, None)
            sketch = self.sketch
        # 512-row chunks: the per-call quantile budget then feeds the
        # estimators a real sample of the whole matrix (a one-time cost
        # at fit start, ~100ms per million rows)
        for i in range(0, len(x), 512):
            sketch.add_batch(x[i:i + 512])

    def snapshot(self) -> dict | None:
        self._flush()
        return self.sketch.snapshot()


# ---- process-global hooks (mirror obs.trace / obs.slo) ----

_active: DataDriftMonitor | None = None
_train: TrainDataSketch | None = None


def install(monitor: DataDriftMonitor) -> DataDriftMonitor:
    global _active
    _active = monitor
    return monitor


def uninstall() -> None:
    global _active
    _active = None


def active() -> DataDriftMonitor | None:
    return _active


def install_train(sketch: TrainDataSketch) -> TrainDataSketch:
    global _train
    _train = sketch
    return sketch


def uninstall_train() -> None:
    global _train
    _train = None


def train_active() -> TrainDataSketch | None:
    return _train


def baseline_from_journal(journal_base: str) -> dict | None:
    """Reconstruct a train-side feature snapshot from a fleet's
    journals: the LAST ``data_stats`` event per train-plane worker,
    merged count-weighted.  The fleet export path uses this — the
    submitter process restores weights from the checkpoint, but the
    data flowed through the WORKERS' processes, whose sketches live in
    their journal siblings."""
    from shifu_tensorflow_tpu.obs.journal import read_events

    latest: dict[Any, tuple] = {}
    for ev in read_events(journal_base):
        if ev.get("event") == "data_stats" and ev.get("plane") == "train":
            stats = ev.get("stats")
            if isinstance(stats, dict) and stats.get("rows"):
                latest[ev.get("worker")] = (ev.get("ts", 0.0), stats)
    if not latest:
        return None
    # oldest-first by event time: if the workers' schemas ever disagree
    # (a mid-job width change), merge_snapshots keeps the NEWEST width
    ordered = sorted(latest.values(), key=lambda t: t[0])
    return merge_snapshots([s for _, s in ordered])
