"""Lazy loader for the native (C++) pieces.

The shared objects are built by ``make -C cpp`` into this directory.  The
loader runs the (mtime-aware, atomic-rename) build on every first load so a
source change can't leave a stale binary silently diverging from the Python
fallback; if the build fails or no toolchain exists it returns None —
callers keep their pure-Python fallback, so the framework works (slower)
without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(_DIR)), "cpp")

_lock = threading.Lock()
_cache: dict[str, "ctypes.CDLL | None"] = {}


def _try_build() -> None:
    if not os.path.isdir(_CPP_DIR):
        return
    try:
        subprocess.run(
            ["make", "-C", _CPP_DIR],
            capture_output=True,
            timeout=120,
            check=False,
        )
    except Exception:
        pass


def load(name: str) -> "ctypes.CDLL | None":
    """Load ``lib<name>.so`` from this directory, (re)building first."""
    with _lock:
        if name in _cache:
            return _cache[name]
        path = os.path.join(_DIR, f"lib{name}.so")
        # always run make, not just when the .so is missing: it is
        # mtime-aware (a fast no-op when fresh) and a stale binary from
        # older sources would silently break Python/native parity
        _try_build()
        lib: "ctypes.CDLL | None" = None
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                lib = None
        _cache[name] = lib
        return lib
