"""Serving-artifact export.

Parity surface: at the end of training the reference's chief rebuilds a
clean inference graph, restores the last checkpoint, and writes a TF
SavedModel with signature ``shifu_input_0`` → ``shifu_output_0``, tag
``serve``, plus a ``GenericModelConfig.json`` whose exact contents Java-side
batch eval consumes (reference: ssgd_monitor.py:457-502,
TensorflowModel.java:112-172).

This module writes BOTH:

1. the same TF SavedModel contract via jax2tf (when TensorFlow is
   importable) — drop-in for the reference's Java/JNI scorer;
2. a framework-native bundle — ``shifu_tpu_model.json`` (architecture =
   the ModelConfig train params + feature schema) + ``shifu_tpu_weights.npz``
   (flat param arrays) — loadable with zero TF dependency by the Python
   scorer (export/eval_model.py) and the C++ scorer (cpp/scorer.cc).

``GenericModelConfig.json`` content matches the reference byte-for-byte in
its required fields (export_generic_config, ssgd_monitor.py:476-490).
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping

import jax
import numpy as np

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.utils import fs

# digest + atomic-publish primitives shared with the serving verifier
# (serve/model_store.py) — writer and checker must never drift
from shifu_tensorflow_tpu.utils.integrity import (
    commit_bytes as _commit_bytes,
    digest_entry as _digest_entry,
)

INPUT_NAME = "shifu_input_0"
OUTPUT_NAME = "shifu_output_0"
SERVE_TAG = "serve"
GENERIC_CONFIG = "GenericModelConfig.json"
NATIVE_ARCH = "shifu_tpu_model.json"
NATIVE_WEIGHTS = "shifu_tpu_weights.npz"
#: per-shard weight files of a mesh-aware export (model-sharded trainer):
#: ``shifu_tpu_weights.shard<k>of<M>.npz``, one per model-mesh coordinate,
#: each digested into the manifest like any artifact.  The manifest's
#: ``weights_sharding`` record (num_shards + per-leaf concat dim/offsets)
#: is what reassembles them; the flat NATIVE_WEIGHTS file is absent from
#: such bundles.  The bundle's identity ``sha256`` stays the digest of
#: the LOGICAL flat npz (assembled in memory at export — export is off
#: the training hot path), so identity is invariant to how the trainer
#: happened to be sharded and the AOT generation guard keeps working
#: across a reshard.
NATIVE_WEIGHTS_SHARD_PREFIX = "shifu_tpu_weights.shard"


def native_weights_shard_name(k: int, num: int) -> str:
    return f"{NATIVE_WEIGHTS_SHARD_PREFIX}{k}of{num}.npz"


#: sidecar manifest over the native bundle (size + CRC32 + SHA-256 per
#: file, the PR-2 verified-checkpoint scheme applied to exports): the
#: serving hot-reload path admits a new artifact only after the manifest
#: verifies, so a partially-written or bit-rotted export is never served.
#: Written LAST (after every file it covers commits), so a manifest's
#: presence implies a complete bundle.
NATIVE_MANIFEST = "shifu_tpu_export.manifest.json"
#: training-side per-feature distribution sketch (obs/datastats.py
#: snapshot: count/mean/std/min/max/missing/inf rates + P² quantiles
#: per feature) shipped WITH the bundle — the serve-side skew
#: detector's baseline.  Covered by the manifest like every artifact: a
#: bit-flipped stats file refuses admission, because a model silently
#: drift-checked against corrupt statistics is worse than one not
#: drift-checked at all.  Optional: bundles exported without the obs
#: data leg simply omit it (and serving skips drift detection).
FEATURE_STATS = "feature_stats.json"


def generic_model_config_json() -> str:
    """The exact JSON the reference writes (ssgd_monitor.py:476-490)."""
    return (
        "{\n"
        '    "inputnames": [\n'
        f'        "{INPUT_NAME}"\n'
        "      ],\n"
        '    "properties": {\n'
        '         "algorithm": "tensorflow",\n'
        '         "tags": ["serve"],\n'
        f'         "outputnames": "{OUTPUT_NAME}",\n'
        '         "normtype": "ZSCALE"\n'
        "      }\n"
        "}"
    )


def _flatten_params(params) -> dict[str, np.ndarray]:
    """'/a/b/kernel' -> array; unwraps flax Partitioned boxes."""
    import flax.linen as nn

    flat = {}

    def walk(prefix: str, tree):
        if isinstance(tree, Mapping):
            for k, v in tree.items():
                walk(f"{prefix}/{k}", v)
        else:
            if isinstance(tree, nn.Partitioned):
                tree = tree.value
            flat[prefix] = np.asarray(jax.device_get(tree))

    walk("", params)
    return flat


def _unflatten_params(flat: Mapping[str, np.ndarray]):
    tree: dict[str, Any] = {}
    for path, arr in flat.items():
        parts = [p for p in path.split("/") if p]
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def _split_sharded_params(params):
    """Flatten params into ``(flat_full, shard_flats, sharding_meta,
    mesh_shape)``.

    ``flat_full`` is the complete logical tree ('/a/b/kernel' -> full
    array) — the bundle identity and the AOT compile input.  When any
    leaf is live model-sharded, ``shard_flats[k]`` holds the flat dict
    for model coordinate k (replicated leaves ride in shard 0 only,
    sharded leaves contribute their k-th block) and ``sharding_meta``
    maps each sharded flat name to ``{"dim", "offsets"}``; otherwise
    both are None and ``mesh_shape`` is ``"unsharded"``."""
    import flax.linen as nn

    from shifu_tensorflow_tpu.parallel.sharding import (
        model_shard_blocks,
        model_shard_info,
    )

    leaves: list[tuple[str, Any]] = []

    def walk(prefix: str, tree):
        if isinstance(tree, Mapping):
            for k, v in tree.items():
                walk(f"{prefix}/{k}", v)
        else:
            if isinstance(tree, nn.Partitioned):
                tree = tree.value
            leaves.append((prefix, tree))

    walk("", params)
    infos = {name: model_shard_info(leaf) for name, leaf in leaves}
    num = max((i[1] for i in infos.values() if i is not None), default=1)
    mesh_shape = "unsharded"
    if num > 1:
        for name, leaf in leaves:
            if infos[name] is not None:
                mesh_shape = ",".join(
                    f"{n}:{s}" for n, s in leaf.sharding.mesh.shape.items()
                )
                break
    flat_full: dict[str, np.ndarray] = {}
    shard_flats: list[dict] = [dict() for _ in range(num)]
    sharding_meta: dict[str, dict] = {}
    for name, leaf in leaves:
        info = infos[name]
        extracted = None
        if info is not None and info[1] == num:
            extracted = model_shard_blocks(leaf, info[0], num)
        if extracted is None:
            full = np.asarray(jax.device_get(leaf))
            flat_full[name] = full
            shard_flats[0][name] = full
            continue
        starts, blocks = extracted
        dim = info[0]
        for k, block in enumerate(blocks):
            shard_flats[k][name] = block
        flat_full[name] = (
            np.concatenate(blocks, axis=dim) if len(blocks) > 1 else blocks[0]
        )
        sharding_meta[name] = {
            "dim": dim,
            "offsets": [int(v) for v in starts] + [int(leaf.shape[dim])],
        }
    if not sharding_meta:
        return flat_full, None, None, "unsharded"
    return flat_full, shard_flats, sharding_meta, mesh_shape


def export_native_bundle(
    export_dir: str,
    params,
    model_config: ModelConfig,
    num_features: int,
    feature_columns=None,
    zscale_means=None,
    zscale_stds=None,
    feature_stats=None,
    aot_buckets=None,
    lineage=None,
) -> None:
    """Write the TF-free artifact: architecture JSON + weights npz, plus
    the sidecar manifest (size+CRC32+SHA-256 per file) that the serving
    reload path verifies before admitting the bundle.  Every file commits
    via tmp+rename; the manifest commits last.

    ``lineage`` (optional) is the generation-lineage stamp: a mapping
    with ``parent_sha256`` (the weights digest of the bundle this one
    was retrained FROM — the rollback target, identifiable from
    artifacts alone) and ``generation`` (monotonic int).  Stamped into
    the manifest as a ``lineage`` object; legacy bundles simply lack
    the key and every reader treats absent lineage as generation 0
    with no parent.

    ``feature_stats`` is the training data's per-feature sketch snapshot
    (obs/datastats.DataSketch.snapshot) — written as
    ``feature_stats.json`` and digested into the manifest, so the serve
    admission that verifies the weights verifies the drift baseline with
    them.

    ``aot_buckets`` (a bucket-ladder tuple — export/aot.py) additionally
    compiles the scorer for each bucket on THIS environment and ships
    the serialized executables under ``aot/``, digested into the
    manifest like every artifact: serve admission then deserializes
    instead of compiling, falling back per bucket on any mismatch."""
    fs.mkdirs(export_dir)
    arch = {
        "format_version": 1,
        "input_name": INPUT_NAME,
        "output_name": OUTPUT_NAME,
        "num_features": int(num_features),
        "feature_columns": list(feature_columns or range(num_features)),
        "model_config": {
            "train": {
                "numTrainEpochs": model_config.num_train_epochs,
                "validSetRate": model_config.valid_set_rate,
                "params": {
                    "NumHiddenLayers": model_config.params.num_hidden_layers,
                    "NumHiddenNodes": list(model_config.params.num_hidden_nodes),
                    "ActivationFunc": list(model_config.params.activation_funcs),
                    "LearningRate": model_config.params.learning_rate,
                    "Optimizer": model_config.params.optimizer,
                    "ModelType": model_config.params.model_type,
                    "WideColumnNums": list(model_config.params.wide_column_nums),
                    "CrossHashSize": model_config.params.cross_hash_size,
                    "NumTasks": model_config.params.num_tasks,
                    "EmbeddingColumnNums": list(model_config.params.embedding_columns),
                    "EmbeddingHashSize": model_config.params.embedding_hash_size,
                    "EmbeddingDim": model_config.params.embedding_dim,
                    "SeqLen": model_config.params.seq_len,
                    "SeqDModel": model_config.params.seq_d_model,
                    "SeqHeads": model_config.params.seq_heads,
                    "SeqBlocks": model_config.params.seq_blocks,
                    # serving is single-device: full attention always,
                    # and no remat (a training-only memory lever —
                    # jax2tf should not trace through jax.checkpoint)
                    "SeqAttention": "full",
                    "SeqRemat": False,
                },
            }
        },
        "normalization": {
            "normtype": "ZSCALE",
            "means": list(map(float, zscale_means)) if zscale_means is not None else None,
            "stds": list(map(float, zscale_stds)) if zscale_stds is not None else None,
        },
    }
    import io

    from shifu_tensorflow_tpu.utils import faults

    arch_bytes = json.dumps(arch, indent=2).encode("utf-8")
    flat, shard_flats, weights_sharding, mesh_shape = (
        _split_sharded_params(params))
    # serialize the npz to memory first so the manifest digests cover
    # exactly the bytes handed to the filesystem (same rationale as
    # NpzCheckpointer._write): any later divergence between manifest and
    # file IS corruption, by construction.  For a sharded export this
    # LOGICAL flat npz is never written — it exists to give the bundle a
    # sharding-invariant identity digest (and the AOT compile its input)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    weights_bytes = buf.getvalue()
    generic_bytes = generic_model_config_json().encode("utf-8")
    weights_entry = _digest_entry(weights_bytes)  # hash the payload once
    files = {
        NATIVE_ARCH: _digest_entry(arch_bytes),
        GENERIC_CONFIG: _digest_entry(generic_bytes),
    }
    shard_payloads: dict[str, bytes] = {}
    if shard_flats is None:
        files[NATIVE_WEIGHTS] = weights_entry
    else:
        # mesh-aware export: one digested npz per model-mesh coordinate;
        # the serve verifier iterates manifest["files"] generically, so
        # shard files verify exactly like the flat file did
        num = len(shard_flats)
        for k, shard in enumerate(shard_flats):
            sbuf = io.BytesIO()
            np.savez(sbuf, **shard)
            payload = sbuf.getvalue()
            name = native_weights_shard_name(k, num)
            shard_payloads[name] = payload
            files[name] = _digest_entry(payload)
    aot_files: dict[str, bytes] = {}
    if aot_buckets:
        # compile + serialize the ladder FROM the bundle's own
        # representation (arch dict + flat arrays — the exact tree the
        # serve side rebuilds), then digest the files into the manifest
        # so the admission that verifies the weights verifies the
        # executables with them
        from shifu_tensorflow_tpu.export import aot as aot_mod

        aot_files = aot_mod.build_aot_files(
            arch, flat, aot_buckets,
            model_name=(os.path.basename(export_dir.rstrip("/"))
                        or None),
            weights_sha256=weights_entry["sha256"],
            mesh_shape=mesh_shape)
        for name, payload in aot_files.items():
            files[name] = _digest_entry(payload)
    stats_bytes = None
    if feature_stats is not None:
        stats_bytes = json.dumps({
            "format_version": 1,
            "feature_columns": list(feature_columns or
                                    range(num_features)),
            "stats": feature_stats,
        }, indent=2).encode("utf-8")
        files[FEATURE_STATS] = _digest_entry(stats_bytes)
    manifest_doc: dict[str, Any] = {
        "format_version": 1,
        "sha256": weights_entry["sha256"],  # bundle identity
        # the mesh the exporter's params lived on ("unsharded" for any
        # model axis of 1): the AOT loader compares this against the
        # fingerprint its executables were compiled under
        "mesh_shape": mesh_shape,
        "files": files,
        "written_by": str(os.getpid()),
    }
    if weights_sharding is not None:
        manifest_doc["weights_sharding"] = {
            "num_shards": len(shard_flats),
            "leaves": weights_sharding,
        }
    if lineage:
        # generation lineage: who this bundle was retrained from.  Kept
        # to the two documented keys (plus anything the caller stamps)
        # so the manifest stays a flat, diffable record.
        stamp = dict(lineage)
        if stamp.get("parent_sha256") is not None:
            stamp["parent_sha256"] = str(stamp["parent_sha256"])
        if stamp.get("generation") is not None:
            stamp["generation"] = int(stamp["generation"])
        manifest_doc["lineage"] = stamp
    manifest = json.dumps(manifest_doc, indent=2)
    # at-rest corruption seam (chaos drills): applied AFTER the digests,
    # so the manifest records what SHOULD land on disk — the serving
    # reload verification must catch the divergence
    weights_bytes = faults.mutate("export.at-rest", weights_bytes)
    # torn-write seam on every publish: a firing export.commit term
    # leaves a truncated tmp beside the previous intact generation —
    # the admission verifier must keep serving the old one
    _commit_bytes(os.path.join(export_dir, NATIVE_ARCH), arch_bytes,
                  site="export.commit")
    if shard_flats is None:
        _commit_bytes(os.path.join(export_dir, NATIVE_WEIGHTS), weights_bytes,
                      site="export.commit")
    else:
        for name, payload in shard_payloads.items():
            payload = faults.mutate("export.at-rest", payload)
            _commit_bytes(os.path.join(export_dir, name), payload,
                          site="export.commit")
    # a re-export under a different mesh must not leave the OTHER
    # layout's weight files beside a manifest that no longer covers
    # them — a legacy manifest-less reader would happily load the stale
    # flat npz of a bundle whose real weights are the shard files
    try:
        for leftover in os.listdir(export_dir):
            stale_flat = (shard_flats is not None
                          and leftover == NATIVE_WEIGHTS)
            stale_shard = (
                leftover.startswith(NATIVE_WEIGHTS_SHARD_PREFIX)
                and leftover not in shard_payloads)
            if stale_flat or stale_shard:
                os.remove(os.path.join(export_dir, leftover))
    except OSError:
        pass
    _commit_bytes(os.path.join(export_dir, GENERIC_CONFIG), generic_bytes,
                  site="export.commit")
    if aot_files:
        from shifu_tensorflow_tpu.export.aot import AOT_DIR as _AOT_DIR

        fs.mkdirs(os.path.join(export_dir, _AOT_DIR))
        for name, payload in aot_files.items():
            _commit_bytes(os.path.join(export_dir, name), payload)
        # prune bucket files a previous generation wrote that this one
        # did not (a narrower ladder): nothing vouches for them anymore
        # and the weights-generation stamp inside the meta no longer
        # names them
        try:
            for leftover in os.listdir(os.path.join(export_dir, _AOT_DIR)):
                rel = f"{_AOT_DIR}/{leftover}"
                if rel not in aot_files and not leftover.startswith("."):
                    os.remove(os.path.join(export_dir, _AOT_DIR, leftover))
        except OSError:
            pass
    else:
        # a re-export WITHOUT AOT must not leave a previous generation's
        # executables beside weights they were not compiled for: the
        # stamped weights digest would refuse them anyway (EvalModel
        # checks it), but stale artifacts beside a manifest that no
        # longer covers them are exactly the chimera the manifest chain
        # exists to prevent
        import shutil

        from shifu_tensorflow_tpu.export.aot import AOT_DIR as _AOT_DIR

        shutil.rmtree(os.path.join(export_dir, _AOT_DIR),
                      ignore_errors=True)
    if stats_bytes is not None:
        _commit_bytes(os.path.join(export_dir, FEATURE_STATS), stats_bytes)
    else:
        # a re-export WITHOUT stats must not leave a stale baseline from
        # a previous generation beside a manifest that no longer vouches
        # for it (the loader only trusts manifest-covered stats, but a
        # legacy manifest-less reader would happily read the orphan)
        try:
            os.remove(os.path.join(export_dir, FEATURE_STATS))
        except OSError:
            pass
    # manifest LAST: its presence implies every covered file committed
    _commit_bytes(
        os.path.join(export_dir, NATIVE_MANIFEST), manifest.encode("utf-8"),
        site="export.commit",
    )


def load_native_weights(model_dir: str) -> dict[str, np.ndarray]:
    """Flat ``{'/a/b/kernel': array}`` from EITHER bundle layout: the flat
    ``shifu_tpu_weights.npz``, or a mesh-aware export's per-shard files
    reassembled via the manifest's ``weights_sharding`` record.  Loading
    is off the training hot path, so the reassembly concat is the work
    itself, not a contract violation.  Integrity is the caller's
    (manifest verifier's) business, exactly as for the flat file."""
    flat_path = os.path.join(model_dir, NATIVE_WEIGHTS)
    if fs.exists(flat_path):
        with fs.open_read(flat_path) as f:
            npz = np.load(f)
            return {k: npz[k] for k in npz.files}
    try:
        with fs.open_read(os.path.join(model_dir, NATIVE_MANIFEST)) as f:
            manifest = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError) as e:
        raise FileNotFoundError(
            f"{model_dir}: no {NATIVE_WEIGHTS} and no readable manifest "
            f"({e})"
        ) from e
    ws = manifest.get("weights_sharding")
    if not isinstance(ws, dict):
        raise FileNotFoundError(
            f"{model_dir}: no {NATIVE_WEIGHTS} and the manifest records "
            f"no weights_sharding — not a native bundle"
        )
    num = int(ws.get("num_shards", 0))
    leaves_meta = ws.get("leaves") or {}
    parts: dict[str, list[np.ndarray]] = {}
    for k in range(num):
        path = os.path.join(model_dir, native_weights_shard_name(k, num))
        with fs.open_read(path) as f:
            npz = np.load(f)
            for name in npz.files:
                parts.setdefault(name, []).append(npz[name])
    flat: dict[str, np.ndarray] = {}
    for name, blocks in parts.items():
        ent = leaves_meta.get(name)
        if ent is not None and len(blocks) > 1:
            flat[name] = np.concatenate(blocks, axis=int(ent["dim"]))
        else:
            flat[name] = blocks[0]
    return flat


def is_native_bundle(path: str) -> bool:
    """A directory is a native bundle when it carries the flat weights
    file OR a manifest (mesh-aware exports have no flat npz)."""
    return os.path.isfile(os.path.join(path, NATIVE_WEIGHTS)) or \
        os.path.isfile(os.path.join(path, NATIVE_MANIFEST))


def bundle_lineage(export_dir: str) -> dict[str, Any]:
    """Read a bundle's identity + lineage from its manifest alone:
    ``{"sha256": <weights digest> | None, "parent_sha256": ... | None,
    "generation": int}``.  Legacy bundles (no ``lineage`` key, or no
    manifest at all) come back as generation 0 with no parent — absent
    lineage is not an error, it is the pre-lifecycle world."""
    out: dict[str, Any] = {"sha256": None, "parent_sha256": None,
                           "generation": 0}
    try:
        with open(os.path.join(export_dir, NATIVE_MANIFEST)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return out
    out["sha256"] = doc.get("sha256")
    lin = doc.get("lineage") or {}
    if isinstance(lin, dict):
        out["parent_sha256"] = lin.get("parent_sha256")
        try:
            out["generation"] = int(lin.get("generation") or 0)
        except (TypeError, ValueError):
            out["generation"] = 0
    return out


def export_saved_model(
    export_dir: str,
    apply_fn,
    params,
    num_features: int,
) -> bool:
    """jax2tf → TF SavedModel with the reference's exact signature.  Returns
    False (skipping quietly) when TensorFlow isn't importable — the native
    bundle is the always-available artifact."""
    try:
        import tensorflow as tf
        from jax.experimental import jax2tf
    except Exception:
        return False

    import flax.linen as nn

    def unboxed(tree):
        return jax.tree_util.tree_map(
            lambda x: x.value if isinstance(x, nn.Partitioned) else x,
            tree,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )

    host_params = jax.device_get(unboxed(params))

    def infer(x):
        return apply_fn({"params": host_params}, x)

    tf_fn = tf.function(
        jax2tf.convert(
            infer,
            with_gradient=False,
            # dynamic batch dimension in the serving signature
            polymorphic_shapes=[f"(b, {num_features})"],
        ),
        autograph=False,
        input_signature=[
            tf.TensorSpec([None, num_features], tf.float32, name=INPUT_NAME)
        ],
    )

    module = tf.Module()
    module.f = tf_fn

    @tf.function(
        input_signature=[
            tf.TensorSpec([None, num_features], tf.float32, name=INPUT_NAME)
        ]
    )
    def serving(x):
        return {OUTPUT_NAME: module.f(x)}

    module.serving = serving
    tf.saved_model.save(
        module,
        export_dir,
        signatures={
            tf.saved_model.DEFAULT_SERVING_SIGNATURE_DEF_KEY: serving
        },
    )
    # atomic commit (same bytes the native bundle wrote, so the export
    # manifest stays valid): an in-place truncate-and-rewrite would hand a
    # concurrently-verifying hot-reload scorer an empty file
    _commit_bytes(
        os.path.join(export_dir, GENERIC_CONFIG),
        generic_model_config_json().encode("utf-8"),
    )
    return True


def export_model(
    export_dir: str,
    trainer,
    *,
    feature_columns=None,
    zscale_means=None,
    zscale_stds=None,
    feature_stats=None,
    aot_buckets=None,
    lineage=None,
) -> dict[str, bool]:
    """One-call export of both artifacts from a Trainer.

    The serving function is REBUILT mesh-less (single-device) instead of
    reusing ``trainer.model.apply``: a trainer on a mesh may have baked
    collective ops into its model — ring/Ulysses attention's shard_map, a
    'model'-sharded embedding's partitioned gather — and jax2tf would trace
    those device-bound collectives into the SavedModel.  The rebuilt module
    resolves to single-device implementations (full attention, local
    lookup); parameters are identical, so scores are too.
    """
    import copy

    from shifu_tensorflow_tpu.models.factory import build_model

    if feature_columns is None:
        # the training graph's column positions ARE the serving contract;
        # fall back to what the trainer was built with
        feature_columns = getattr(trainer, "feature_columns", None)
    # keep-best (Trainer(keep_best=...)): serve the best validation epoch,
    # not the last — that is what "keep best" promises
    export_params = trainer.state.params
    using_best = getattr(trainer, "best_params", None) is not None
    if using_best:
        export_params = trainer.best_params
    if getattr(trainer, "_host_emb", None) is not None:
        # EmbeddingPlacement=host: serving has no host process, so the
        # artifact converts to the standard DEVICE-embedding bundle — the
        # table becomes /hashed_columns/table and the arch (which never
        # carries the placement key) rebuilds EmbeddingAugmented; hashing
        # is bit-identical host/device (models/host_embedding.bucket_ids
        # vs ops/hashing), so scores match across every backend.
        table = (trainer.best_host_table
                 if using_best and trainer.best_host_table is not None
                 else trainer._host_emb.table)
        export_params = {
            "hashed_columns": {"table": np.asarray(table)},
            "base": export_params,
        }
    if feature_stats is None:
        # bundle-shipped drift baseline: the process-wide train data
        # sketch (obs/datastats.py), fed by this trainer's ingest taps —
        # shipped only when its width matches the serving contract (a
        # second trainer of a different width in this process resets the
        # sketch; never ship a mismatched baseline)
        from shifu_tensorflow_tpu.obs import datastats as obs_datastats

        sk = obs_datastats.train_active()
        if sk is not None:
            snap = sk.snapshot()
            if snap is not None and \
                    snap["num_features"] == trainer.num_features:
                feature_stats = snap
    export_native_bundle(
        export_dir,
        export_params,
        trainer.model_config,
        trainer.num_features,
        feature_columns=feature_columns,
        zscale_means=zscale_means,
        zscale_stds=zscale_stds,
        feature_stats=feature_stats,
        aot_buckets=aot_buckets,
        lineage=lineage,
    )
    # deep-copy: ModelConfig.from_json keeps a reference to the nested
    # dicts, so mutating a shallow copy would rewrite the live trainer's
    # config (and every future WorkerConfig/re-export built from it)
    raw = copy.deepcopy(trainer.model_config.raw)
    if getattr(trainer, "_host_emb", None) is not None:
        # the serving graph embeds on-device (the converted bundle above)
        raw.setdefault("train", {}).setdefault(
            "params", {})["EmbeddingPlacement"] = "device"
    if trainer.model_config.params.seq_len > 0:
        # force single-device attention regardless of how training ran,
        # and drop remat (training-only; jax2tf shouldn't trace through
        # jax.checkpoint)
        serve_params = raw.setdefault("train", {}).setdefault("params", {})
        serve_params["SeqAttention"] = "full"
        serve_params["SeqRemat"] = False
    serve_mc = ModelConfig.from_json(raw)
    serve_model = build_model(
        serve_mc,
        tuple(feature_columns) if feature_columns else None,
        shard_embeddings=False,
        # 'auto' could resolve to the Pallas TPU kernel on a TPU backend,
        # which jax2tf would bake (TPU-only) into the SavedModel; the
        # portable gather is the only correct serving lookup
        embedding_impl="xla",
    )
    from flax.core import meta as flax_meta

    serve_params = jax.tree_util.tree_map(
        lambda x: x.unbox() if isinstance(x, flax_meta.AxisMetadata) else x,
        export_params,  # same tree both artifacts: best epoch when kept
        is_leaf=lambda x: isinstance(x, flax_meta.AxisMetadata),
    )
    ok_tf = export_saved_model(
        export_dir, serve_model.apply, serve_params, trainer.num_features
    )
    return {"native": True, "saved_model": ok_tf}
