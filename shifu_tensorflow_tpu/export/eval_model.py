"""Batch scoring against an exported artifact.

Parity surface: the reference's Java ``TensorflowModel implements
Computable`` — ``init(GenericModelConfig)`` loads the SavedModel bundle,
``compute(MLData)`` converts a row of doubles to a float tensor, feeds
``shifu_input_0``, fetches ``shifu_output_0``, returns the scalar
(TensorflowModel.java:32,53-94,112-172).  ``EvalModel`` mirrors that
lifecycle (init → compute/compute_batch → release) with three backends:

- ``native``: rebuilds the flax model from ``shifu_tpu_model.json`` and
  loads ``shifu_tpu_weights.npz`` — zero TF dependency;
- ``saved_model``: loads the TF SavedModel through TensorFlow when
  available, scoring through the exact signature the Java evaluator uses —
  this is the cross-check that the exported artifact honors the contract;
- ``cpp``: the C++ scorer (cpp/stpu_scorer.cc via ctypes) — the
  zero-Python-runtime path matching the reference's JNI evaluator; covers
  every exported family except sequence (dnn, wide&deep, multi-task,
  embedding-augmented).

Thread-safety contract: an ``EvalModel`` instance is internally
synchronized — ``compute``/``compute_batch``/``release`` serialize on a
per-instance lock, because none of the backends tolerates concurrent
entry (the cpp backend shares one ctypes handle, the saved_model backend
one TF session, and ``release`` tears state down under a running call).
Concurrent callers are therefore CORRECT but not parallel; for
throughput, coalesce rows into one ``compute_batch`` call (what the
serving micro-batcher, serve/batcher.py, exists for) or hold one
instance per thread.

The ``native`` backend pads every batch up to the export/bucketing.py
ladder before dispatch, so the jitted scorer compiles once per BUCKET
instead of once per distinct batch length (a free-varying workload would
otherwise re-trace per length, ~19 ms each on the flagship DNN);
``native_trace_count`` exposes the compile count for regression tests.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

from shifu_tensorflow_tpu.export.bucketing import bucket_size, pad_rows

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.export.saved_model import (
    GENERIC_CONFIG,
    INPUT_NAME,
    NATIVE_ARCH,
    OUTPUT_NAME,
    _unflatten_params,
    load_native_weights,
)
from shifu_tensorflow_tpu.utils import fs, logs

log = logs.get("export.eval")


class ModelReleasedError(RuntimeError):
    """compute after release(): the instance's backend state is gone.
    Raised as a distinct type so a holder of a stale reference (the
    serving hot-reload swap window) can re-fetch the live model instead
    of surfacing an opaque AttributeError."""


class EvalModel:
    """init/compute/release lifecycle over an exported model dir."""

    def __init__(self, model_dir: str, backend: str = "native"):
        self.model_dir = model_dir
        self.backend = backend
        # serializes compute/compute_batch/release — see the module
        # docstring's thread-safety contract.  RLock: compute() calls
        # compute_batch() on the same thread.
        self._compute_lock = threading.RLock()
        self.generic_config = json.loads(
            fs.read_text(os.path.join(model_dir, GENERIC_CONFIG))
        )
        assert INPUT_NAME in self.generic_config["inputnames"]
        if backend == "native":
            self._init_native()
        elif backend == "saved_model":
            self._init_saved_model()
        elif backend == "cpp":
            self._init_cpp()
        else:
            raise ValueError(f"unknown backend {backend!r}")

    # ---- native backend ----
    def _init_native(self) -> None:
        from shifu_tensorflow_tpu.models.factory import build_model

        arch = json.loads(fs.read_text(os.path.join(self.model_dir, NATIVE_ARCH)))
        self.num_features = int(arch["num_features"])
        mc = ModelConfig.from_json(arch["model_config"])
        feature_columns = tuple(arch.get("feature_columns") or ())
        self._model = build_model(mc, feature_columns or None)
        # both layouts: flat npz, or a mesh-aware export's shard files
        self._params = _unflatten_params(load_native_weights(self.model_dir))
        norm = arch.get("normalization") or {}
        self._means = np.asarray(norm["means"], np.float32) if norm.get("means") else None
        self._stds = np.asarray(norm["stds"], np.float32) if norm.get("stds") else None

        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        # weights live on device once — numpy leaves would be re-copied
        # host->device on EVERY dispatch, taxing the per-row path
        self._params = jax.device_put(self._params)
        # jit the forward: un-jitted flax apply re-TRACES the model every
        # call (~19ms for the flagship DNN — measured 53 rows/s on the
        # per-row Computable path); compiled per input shape it serves
        # per-row scoring at tens of microseconds.  Batches pad to the
        # bucketing ladder before dispatch (compute_batch), so the trace
        # count is bounded by the ladder, not by how many distinct batch
        # lengths the workload happens to produce.
        model = self._model
        self._trace_count = 0

        def fwd(params, x):
            # runs at TRACE time only — counts compilations, not calls
            self._trace_count += 1
            return model.apply({"params": params}, x)

        # compile flight recorder seam (obs/compile.py): each bucket's
        # compile journals a `compile` event naming this bundle — with
        # no recorder installed the wrap is one is-None check per call
        from shifu_tensorflow_tpu.obs import compile as obs_compile

        self._model_name = (os.path.basename(self.model_dir.rstrip("/"))
                            or None)
        self._apply = obs_compile.observe(
            jax.jit(fwd), "eval.native_score",
            model=self._model_name,
            bucket_from=lambda params, x: x.shape[0],
        )
        # AOT executable shipping (export/aot.py): when the bundle ships
        # serialized ladder executables, dispatch DESERIALIZES them
        # instead of compiling — per-bucket, falling back to the jitted
        # path (live compile) on any fingerprint/payload mismatch.  A
        # bundle without aot/ behaves byte-identically to before.
        from shifu_tensorflow_tpu.export import aot as aot_mod

        try:
            self._aot = aot_mod.AotIndex.load(self.model_dir)
        except Exception as e:  # the index must never fail the load
            self._aot = aot_mod.AotIndex(
                self.model_dir, None,
                unusable=f"{type(e).__name__}: {e}")
        self._aot_execs: dict[int, object] = {}
        self._aot_failed: dict[int, str] = {}
        self._aot_loads = 0
        self._aot_fallbacks = 0
        if self._aot is not None and self._aot.unusable:
            log.warning(
                "AOT executables at %s unusable (%s): every shipped "
                "bucket will live-compile instead",
                self.model_dir, self._aot.unusable)

    def _init_cpp(self) -> None:
        from shifu_tensorflow_tpu.export.native_scorer import NativeScorer

        self._cpp = NativeScorer(self.model_dir)
        self.num_features = self._cpp.num_features
        # normalization is applied inside the native scorer
        self._means = self._stds = None

    def _init_saved_model(self) -> None:
        import tensorflow as tf

        self._tf = tf
        loaded = tf.saved_model.load(self.model_dir, tags=None)
        self._infer = loaded.signatures["serving_default"]
        # feature count from the signature input spec
        spec = self._infer.structured_input_signature[1]
        (only,) = spec.values()
        self.num_features = int(only.shape[1])
        # normalization stats live in the native arch file alongside the
        # SavedModel; both backends must apply identical ZSCALE
        self._means = self._stds = None
        arch_path = os.path.join(self.model_dir, NATIVE_ARCH)
        if fs.exists(arch_path):
            norm = json.loads(fs.read_text(arch_path)).get("normalization") or {}
            if norm.get("means"):
                self._means = np.asarray(norm["means"], np.float32)
                self._stds = np.asarray(norm["stds"], np.float32)

    # ---- scoring ----
    def compute(self, row) -> float:
        """Score one row of raw doubles (Computable.compute parity)."""
        out = self.compute_batch(np.asarray(row, np.float32)[None, :])
        return float(out[0, 0])

    def compute_batch(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows, np.float32)
        if rows.shape[1] != self.num_features:
            raise ValueError(
                f"expected {self.num_features} features, got {rows.shape[1]}"
            )
        with self._compute_lock:
            if getattr(self, "_released", False):
                # a caller that dereferenced this instance just before a
                # hot-reload swap can land here AFTER release() won the
                # lock; the typed error lets it re-fetch the live model
                raise ModelReleasedError(self.model_dir)
            if self._means is not None:
                rows = (rows - self._means) / np.where(
                    self._stds == 0, 1, self._stds
                )
            if self.backend == "native":
                n = rows.shape[0]
                # pad to the bucket ladder: compile once per bucket, not
                # once per distinct batch length (padded rows sliced off)
                bucket = bucket_size(n)
                padded = pad_rows(rows, bucket)
                out = self._dispatch(self._jnp.asarray(padded), bucket)
                return np.asarray(out)[:n]
            if self.backend == "cpp":
                return self._cpp.score(rows)
            result = self._infer(**{INPUT_NAME: self._tf.constant(rows)})
            return result[OUTPUT_NAME].numpy()

    def _dispatch(self, x, bucket: int):
        """Route one padded batch (caller holds the compute lock): the
        bundle-shipped AOT executable when one deserializes for this
        bucket, else the jitted scorer (whose first call per bucket
        live-compiles).  When AOT *promised* the bucket and could not
        deliver, the live compile journals ``kind=aot_fallback`` with
        the reason — never ``warm``/unmarked — so admission journals
        say what actually happened."""
        fn = None
        reason = None
        if self._aot is not None:
            fn, reason = self._aot_acquire(bucket)
        if fn is not None:
            return fn(self._params, x)
        if reason is not None:
            from shifu_tensorflow_tpu.obs import compile as obs_compile

            with obs_compile.kind_section("aot_fallback",
                                          aot_error=reason):
                return self._apply(self._params, x)
        return self._apply(self._params, x)

    def _aot_acquire(self, bucket: int):
        """(executable, None) on an AOT hit for ``bucket``; (None,
        reason) when the bundle promised the bucket and cannot deliver
        (the caller live-compiles under ``kind=aot_fallback``); (None,
        None) for buckets the bundle never shipped (plain live path).
        A successful deserialization journals a ``compile`` event with
        ``kind=aot_load`` and ``compile_s`` ~ 0 — admission cost
        becomes visible as what it is: a load, not a compile."""
        fn = self._aot_execs.get(bucket)
        if fn is not None:
            return fn, None
        failed = self._aot_failed.get(bucket)
        if failed is not None:
            return None, failed
        if not self._aot.covers(bucket):
            return None, None
        from shifu_tensorflow_tpu.export.aot import AotLoadError
        from shifu_tensorflow_tpu.obs import compile as obs_compile

        t0 = time.perf_counter()
        try:
            fn = self._aot.load_bucket(bucket)
        except AotLoadError as e:
            reason = str(e)
            self._aot_failed[bucket] = reason
            self._aot_fallbacks += 1
            if not self._aot.unusable:
                # per-bucket warnings only for genuinely per-bucket
                # failures (corrupt payload, CRC): an index-wide
                # mismatch already logged ONE summary warning at init —
                # restating it per bucket x tenant x worker would bury
                # a fleet restart's logs
                log.warning("AOT bucket %d at %s refused (%s); falling "
                            "back to live compile", bucket,
                            self.model_dir, reason)
            return None, reason
        wall = time.perf_counter() - t0
        self._aot_execs[bucket] = fn
        self._aot_loads += 1
        rec = obs_compile.active()
        if rec is not None:
            try:
                import jax

                # ShapeDtypeStruct: the signature needs shape+dtype
                # only — no reason to allocate a (bucket, f) device
                # array just to journal what was loaded
                sig = obs_compile.signature_of(
                    (self._params,
                     jax.ShapeDtypeStruct(
                         (bucket, self.num_features),
                         self._jnp.float32)), {})
            except Exception:
                sig = "?"
            rec.record(name="eval.native_score", signature=sig,
                       compile_s=0.0, parts=0, wall_s=wall,
                       bucket=bucket, model=self._model_name,
                       kind="aot_load")
        return fn, None

    @property
    def aot_stats(self) -> dict:
        """What AOT did for this instance: whether the bundle shipped
        executables, how many buckets deserialized vs fell back to a
        live compile, and why the whole index was unusable (fingerprint
        or generation mismatch) if it was.  Read by the serve admission
        path for its logs/journal."""
        if self.backend != "native" or getattr(self, "_aot", None) is None:
            return {"shipped": False, "loads": 0, "fallbacks": 0,
                    "unusable": None}
        return {
            "shipped": True,
            "loads": self._aot_loads,
            "fallbacks": self._aot_fallbacks,
            "unusable": self._aot.unusable,
        }

    def warm(self, buckets) -> int:
        """Pre-compile the jitted native scorer for every ladder bucket
        in ``buckets`` (row counts), so no future ``compute_batch`` ever
        pays a trace+compile on the request path.  Returns the number of
        NEW traces this call caused (0 when everything was already
        compiled — the pinned-``native_trace_count`` serving invariant).

        The cpp and saved_model backends compile nothing per shape, so
        warming them is a free no-op.  Thread-safe under the same
        per-instance lock as compute; raises
        :class:`ModelReleasedError` after release()."""
        if self.backend != "native":
            return 0
        from shifu_tensorflow_tpu.obs import compile as obs_compile

        with self._compute_lock:
            if getattr(self, "_released", False):
                raise ModelReleasedError(self.model_dir)
            before = self._trace_count
            # warm_section: these compiles journal kind="warm" and never
            # count toward a recompile storm — the ladder pre-warm is
            # deliberate churn (and the storm's cure)
            with obs_compile.warm_section():
                for b in sorted({int(b) for b in buckets}):
                    if b < 1:
                        raise ValueError(f"bucket must be >= 1, got {b}")
                    # zeros are fine: compilation keys on SHAPE, and the
                    # scores of a warm-up batch are never observed.  The
                    # value FETCH matters: dispatch alone returns
                    # futures, and a warm() that only enqueued would let
                    # the model be swapped in while its warm-up programs
                    # still occupy the device — the first real request
                    # would queue behind them, re-creating (a smaller)
                    # latency cliff.  _dispatch prefers the
                    # bundle-shipped AOT executable: a hit deserializes
                    # (~ms, journaled kind=aot_load) instead of
                    # compiling, which is the whole point of shipping
                    # them — warming then costs no new traces at all.
                    x = self._jnp.zeros((b, self.num_features),
                                        self._jnp.float32)
                    np.asarray(self._dispatch(x, b))
            return self._trace_count - before

    def device_bytes(self) -> int:
        """Device-resident bytes this model holds (native backend: the
        weight pytree placed by ``device_put``; other backends hold no
        jax buffers and report 0).  Read by the serve tenancy plane's
        memory accountant so the LRU budget's dashboard shows *device*
        bytes per tenant, not just bundle bytes on disk."""
        if self.backend != "native" or getattr(self, "_released", False):
            return 0
        from shifu_tensorflow_tpu.obs.memory import tree_device_bytes

        return tree_device_bytes(getattr(self, "_params", None))

    @property
    def native_trace_count(self) -> int:
        """How many times the jitted native scorer has (re)traced — flat
        across varying batch lengths within one bucket, by construction."""
        return getattr(self, "_trace_count", 0)

    def release(self) -> None:
        """Explicit resource release (closeTensors parity,
        TensorflowModel.java:97-109) — backends hold no leaked handles, so
        this just drops references.  Takes the compute lock: a release
        racing an in-flight compute (the serving hot-reload swap drops the
        OLD model while the batcher may still be scoring on it) waits for
        the call to finish instead of tearing state down under it."""
        with self._compute_lock:
            self._released = True
            if hasattr(self, "_cpp"):
                self._cpp.close()
            for attr in ("_model", "_params", "_infer", "_tf", "_jnp",
                         "_cpp", "_apply", "_aot", "_aot_execs"):
                if hasattr(self, attr):
                    delattr(self, attr)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
