"""Batch scoring against an exported artifact.

Parity surface: the reference's Java ``TensorflowModel implements
Computable`` — ``init(GenericModelConfig)`` loads the SavedModel bundle,
``compute(MLData)`` converts a row of doubles to a float tensor, feeds
``shifu_input_0``, fetches ``shifu_output_0``, returns the scalar
(TensorflowModel.java:32,53-94,112-172).  ``EvalModel`` mirrors that
lifecycle (init → compute/compute_batch → release) with three backends:

- ``native``: rebuilds the flax model from ``shifu_tpu_model.json`` and
  loads ``shifu_tpu_weights.npz`` — zero TF dependency;
- ``saved_model``: loads the TF SavedModel through TensorFlow when
  available, scoring through the exact signature the Java evaluator uses —
  this is the cross-check that the exported artifact honors the contract;
- ``cpp``: the C++ scorer (cpp/stpu_scorer.cc via ctypes) — the
  zero-Python-runtime path matching the reference's JNI evaluator; covers
  every exported family except sequence (dnn, wide&deep, multi-task,
  embedding-augmented).
"""

from __future__ import annotations

import json
import os

import numpy as np

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.export.saved_model import (
    GENERIC_CONFIG,
    INPUT_NAME,
    NATIVE_ARCH,
    NATIVE_WEIGHTS,
    OUTPUT_NAME,
    _unflatten_params,
)
from shifu_tensorflow_tpu.utils import fs


class EvalModel:
    """init/compute/release lifecycle over an exported model dir."""

    def __init__(self, model_dir: str, backend: str = "native"):
        self.model_dir = model_dir
        self.backend = backend
        self.generic_config = json.loads(
            fs.read_text(os.path.join(model_dir, GENERIC_CONFIG))
        )
        assert INPUT_NAME in self.generic_config["inputnames"]
        if backend == "native":
            self._init_native()
        elif backend == "saved_model":
            self._init_saved_model()
        elif backend == "cpp":
            self._init_cpp()
        else:
            raise ValueError(f"unknown backend {backend!r}")

    # ---- native backend ----
    def _init_native(self) -> None:
        from shifu_tensorflow_tpu.models.factory import build_model

        arch = json.loads(fs.read_text(os.path.join(self.model_dir, NATIVE_ARCH)))
        self.num_features = int(arch["num_features"])
        mc = ModelConfig.from_json(arch["model_config"])
        feature_columns = tuple(arch.get("feature_columns") or ())
        self._model = build_model(mc, feature_columns or None)
        with fs.open_read(os.path.join(self.model_dir, NATIVE_WEIGHTS)) as f:
            npz = np.load(f)
            flat = {k: npz[k] for k in npz.files}
        self._params = _unflatten_params(flat)
        norm = arch.get("normalization") or {}
        self._means = np.asarray(norm["means"], np.float32) if norm.get("means") else None
        self._stds = np.asarray(norm["stds"], np.float32) if norm.get("stds") else None

        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        # weights live on device once — numpy leaves would be re-copied
        # host->device on EVERY dispatch, taxing the per-row path
        self._params = jax.device_put(self._params)
        # jit the forward: un-jitted flax apply re-TRACES the model every
        # call (~19ms for the flagship DNN — measured 53 rows/s on the
        # per-row Computable path); compiled per input shape it serves
        # per-row scoring at tens of microseconds
        model = self._model
        self._apply = jax.jit(
            lambda params, x: model.apply({"params": params}, x)
        )

    def _init_cpp(self) -> None:
        from shifu_tensorflow_tpu.export.native_scorer import NativeScorer

        self._cpp = NativeScorer(self.model_dir)
        self.num_features = self._cpp.num_features
        # normalization is applied inside the native scorer
        self._means = self._stds = None

    def _init_saved_model(self) -> None:
        import tensorflow as tf

        self._tf = tf
        loaded = tf.saved_model.load(self.model_dir, tags=None)
        self._infer = loaded.signatures["serving_default"]
        # feature count from the signature input spec
        spec = self._infer.structured_input_signature[1]
        (only,) = spec.values()
        self.num_features = int(only.shape[1])
        # normalization stats live in the native arch file alongside the
        # SavedModel; both backends must apply identical ZSCALE
        self._means = self._stds = None
        arch_path = os.path.join(self.model_dir, NATIVE_ARCH)
        if fs.exists(arch_path):
            norm = json.loads(fs.read_text(arch_path)).get("normalization") or {}
            if norm.get("means"):
                self._means = np.asarray(norm["means"], np.float32)
                self._stds = np.asarray(norm["stds"], np.float32)

    # ---- scoring ----
    def compute(self, row) -> float:
        """Score one row of raw doubles (Computable.compute parity)."""
        out = self.compute_batch(np.asarray(row, np.float32)[None, :])
        return float(out[0, 0])

    def compute_batch(self, rows: np.ndarray) -> np.ndarray:
        rows = np.asarray(rows, np.float32)
        if rows.shape[1] != self.num_features:
            raise ValueError(
                f"expected {self.num_features} features, got {rows.shape[1]}"
            )
        if self._means is not None:
            rows = (rows - self._means) / np.where(self._stds == 0, 1, self._stds)
        if self.backend == "native":
            out = self._apply(self._params, self._jnp.asarray(rows))
            return np.asarray(out)
        if self.backend == "cpp":
            return self._cpp.score(rows)
        result = self._infer(**{INPUT_NAME: self._tf.constant(rows)})
        return result[OUTPUT_NAME].numpy()

    def release(self) -> None:
        """Explicit resource release (closeTensors parity,
        TensorflowModel.java:97-109) — backends hold no leaked handles, so
        this just drops references."""
        if hasattr(self, "_cpp"):
            self._cpp.close()
        for attr in ("_model", "_params", "_infer", "_tf", "_jnp", "_cpp",
                     "_apply"):
            if hasattr(self, attr):
                delattr(self, attr)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
