"""AOT executable shipping: compile the bucket ladder once, at export.

The serve plane pre-warms the bucketing ladder by *compiling at
admission* (PR-5/9): every restart, hot reload, and SO_REUSEPORT worker
re-pays XLA for programs that never changed, and the cost scales as
tenants x ladder buckets.  The TensorFlow system paper makes XLA AOT
compilation a first-class export artifact for exactly this reason
(PAPERS.md); the PR-10 compile flight recorder tells us which
signatures actually compile in production — the ladder the export
already enumerates (export/bucketing.py).  So: compile those programs
ONCE at export time, serialize the executables
(``jax.experimental.serialize_executable``), and ship them in the
native bundle like any artifact.

Bundle layout (all committed tmp+rename and digested into the PR-3
export manifest, so a torn or bit-rotted executable refuses admission
exactly like corrupt weights)::

    <export_dir>/aot/aot_meta.json     fingerprint + per-bucket index
    <export_dir>/aot/bucket_<n>.bin    pickle((payload, in_tree, out_tree))

A serialized executable is only loadable on the environment that built
it — same jax/jaxlib, same backend, same device kind — so the meta
records a **compile-environment fingerprint**
(:func:`compile_env_fingerprint`).  The load side
(:class:`AotIndex`, consumed by ``EvalModel``) compares fingerprints
and falls back PER BUCKET to a live compile on any mismatch or
deserialization failure: shipping AOT executables must never make a
bundle unservable that could still compile live.  Each bucket file
also carries its own size+CRC32 in the meta, so a standalone
``EvalModel`` (no manifest verification) still refuses a flipped
payload cleanly instead of feeding garbage to the pickle layer.

Fallback ladder at admission, fastest first:

1. **AOT hit** — deserialize the shipped executable (~ms, journaled as
   a ``compile`` event with ``kind=aot_load`` and ``compile_s`` ~ 0);
2. **persistent compilation cache** — a fingerprint-mismatched bucket
   that live-compiles under ``shifu.tpu.compile-cache-dir`` populates
   jax's on-disk cache, so the *next* worker/restart skips XLA anyway
   (:func:`shifu_tensorflow_tpu.obs.compile.apply_persistent_cache`);
3. **live compile** — the PR-5 warm path, journaled ``kind=warm`` (or
   ``kind=aot_fallback`` when AOT promised the bucket and couldn't
   deliver).

Import-light at module top (stdlib + numpy + config/bucketing): the
train CLI resolves ``--export-aot`` before importing jax, and the obs
CLI never imports this module at all.  jax is touched lazily inside
the build/load functions, which only run in jax processes.
"""

from __future__ import annotations

import json
import os
import pickle
import zlib

import numpy as np

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.export.bucketing import ladder
from shifu_tensorflow_tpu.utils import fs, logs

log = logs.get("export.aot")

#: bundle subdirectory holding the serialized executables
AOT_DIR = "aot"
#: the per-bundle AOT index: compile-environment fingerprint + one
#: entry per bucket (file name, size, CRC32)
AOT_META = f"{AOT_DIR}/aot_meta.json"

__all__ = [
    "AOT_DIR",
    "AOT_META",
    "AotIndex",
    "AotExportError",
    "AotLoadError",
    "build_aot_files",
    "compile_env_fingerprint",
    "fingerprint_mismatch",
    "resolve_aot_buckets",
]


class AotLoadError(RuntimeError):
    """One shipped executable cannot be loaded (corrupt payload, backend
    refusal).  Scoped to its bucket: the caller falls back to a live
    compile for that bucket and keeps serving."""


class AotExportError(RuntimeError):
    """The export side cannot build AOT artifacts at all (a jax build
    without executable serialization).  Distinct from
    :class:`AotLoadError` — this is a whole-export capability failure,
    not a per-bucket load fallback; ``--export-aot`` fails loudly
    instead of quietly shipping a bundle without what was asked for."""


def compile_env_fingerprint(mesh_shape: str | None = None) -> dict:
    """The environment a serialized executable is valid in: jax +
    jaxlib versions (the serialization format and the XLA build),
    backend platform, the first device's kind (a CPU executable is
    not a TPU executable; a v4 executable is not a v5e one), and the
    weights mesh shape the program was traced against (a program whose
    parameter shapes are per-shard slices cannot score a differently
    sharded — or unsharded — bundle).  Stamped into ``aot_meta.json``
    at export; compared at load."""
    import jax

    fp = {"jax": getattr(jax, "__version__", "?"),
          "mesh_shape": mesh_shape or "unsharded"}
    try:
        import jaxlib

        fp["jaxlib"] = getattr(jaxlib, "__version__", "?")
    except Exception:
        fp["jaxlib"] = "?"
    try:
        fp["backend"] = jax.default_backend()
        fp["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        fp["backend"] = fp["device_kind"] = "?"
    return fp


def fingerprint_mismatch(
    recorded: dict, mesh_shape: str | None = None
) -> str | None:
    """None when ``recorded`` (from a bundle's meta) matches this
    process's compile environment, else a human-readable reason naming
    the first differing field.  ``mesh_shape`` is the *bundle's*
    current weights layout (its export manifest's ``mesh_shape``,
    default unsharded); it is only compared when the recorded
    fingerprint carries one, so legacy AOT metas admit unchanged."""
    if not isinstance(recorded, dict) or not recorded:
        return "bundle carries no compile-environment fingerprint"
    env = compile_env_fingerprint(mesh_shape=mesh_shape)
    fields = ("jax", "jaxlib", "backend", "device_kind")
    if "mesh_shape" in recorded:
        fields += ("mesh_shape",)
    for field in fields:
        want, have = recorded.get(field), env.get(field)
        if want != have:
            return f"{field} {have!r} != exported {want!r}"
    return None


def bucket_file(bucket: int) -> str:
    return f"{AOT_DIR}/bucket_{int(bucket)}.bin"


def build_aot_files(
    arch: dict,
    flat_params: dict,
    buckets,
    *,
    model_name: str | None = None,
    weights_sha256: str | None = None,
    mesh_shape: str | None = None,
) -> dict[str, bytes]:
    """Compile the scorer for every ladder bucket and serialize the
    executables; returns ``{relative_name: bytes}`` for the export
    writer to commit and digest into the manifest.

    The model and parameter tree are rebuilt FROM the bundle's own
    representation (the arch dict + the flat npz arrays), exactly the
    way ``EvalModel._init_native`` will rebuild them at load — the
    serialized call convention (pytree structure, shapes, dtypes) is
    identical on both sides by construction, not by convention.
    """
    import jax
    import jax.numpy as jnp

    from shifu_tensorflow_tpu.config.model_config import ModelConfig
    from shifu_tensorflow_tpu.export.saved_model import _unflatten_params
    from shifu_tensorflow_tpu.models.factory import build_model
    from shifu_tensorflow_tpu.obs import compile as obs_compile

    try:
        from jax.experimental import serialize_executable as se
    except Exception as e:  # pragma: no cover - jax build without AOT
        raise AotExportError(
            f"this jax build cannot serialize executables: {e}") from e

    mc = ModelConfig.from_json(arch["model_config"])
    feature_columns = tuple(arch.get("feature_columns") or ())
    model = build_model(mc, feature_columns or None)
    num_features = int(arch["num_features"])
    params = jax.device_put(_unflatten_params(
        {k: np.asarray(v) for k, v in flat_params.items()}))

    def fwd(p, x):
        return model.apply({"params": p}, x)

    jitted = jax.jit(fwd)
    files: dict[str, bytes] = {}
    entries: dict[str, dict] = {}
    # export-side compiles attribute to their own callable name: an
    # export running inside an obs-enabled train process journals them
    # as deliberate kind="export" work, never as request-path churn
    with obs_compile.kind_section("export"), \
            obs_compile.attribute("export.aot", model=model_name):
        for b in sorted({int(b) for b in buckets}):
            if b < 1:
                raise ValueError(f"bucket must be >= 1, got {b}")
            x = jnp.zeros((b, num_features), jnp.float32)
            compiled = jitted.lower(params, x).compile()
            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
            name = bucket_file(b)
            files[name] = blob
            entries[str(b)] = {
                "file": name,
                "size": len(blob),
                "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
            }
    meta = {
        "format_version": 1,
        "fingerprint": compile_env_fingerprint(mesh_shape=mesh_shape),
        "num_features": num_features,
        "buckets": entries,
        # which weights generation these programs were compiled WITH —
        # a stale aot/ dir beside re-exported weights must refuse, not
        # deserialize programs whose constants/layout assumptions came
        # from different parameters
        **({"weights_sha256": weights_sha256} if weights_sha256 else {}),
    }
    files[AOT_META] = json.dumps(meta, indent=2, sort_keys=True).encode(
        "utf-8")
    log.info("serialized %d AOT executable(s) (%d bytes total)",
             len(entries), sum(len(v) for v in files.values()))
    return files


class AotIndex:
    """Load-side view of a bundle's shipped executables.

    ``load(model_dir)`` returns None when the bundle ships no AOT at
    all (legacy bundles admit byte-identically to today).  A shipped
    bundle whose meta is unreadable or whose fingerprint does not match
    this environment yields an index with ``unusable`` set — every
    promised bucket then falls back to a live compile, journaled
    ``kind=aot_fallback`` with the reason."""

    def __init__(self, model_dir: str, meta: dict | None,
                 unusable: str | None = None):
        self.model_dir = model_dir
        self.meta = meta
        self.unusable = unusable
        self.buckets: dict[int, dict] = {}
        if meta is not None:
            for b, entry in (meta.get("buckets") or {}).items():
                try:
                    self.buckets[int(b)] = dict(entry)
                except (TypeError, ValueError):
                    continue

    @classmethod
    def load(cls, model_dir: str) -> "AotIndex | None":
        path = os.path.join(model_dir, AOT_META)
        if not fs.exists(path):
            return None
        try:
            meta = json.loads(fs.read_text(path))
            if int(meta.get("format_version", 0)) != 1:
                raise ValueError(
                    f"unknown aot format_version "
                    f"{meta.get('format_version')!r}")
        except (OSError, ValueError) as e:
            # shipped but unreadable: PROMISED and broken — every bucket
            # falls back (and journals why), never refuses the bundle
            return cls(model_dir, None,
                       unusable=f"unreadable {AOT_META}: {e}")
        mismatch = fingerprint_mismatch(
            meta.get("fingerprint") or {},
            mesh_shape=cls._bundle_mesh_shape(model_dir))
        if mismatch is None:
            mismatch = cls._generation_mismatch(model_dir, meta)
        return cls(model_dir, meta, unusable=mismatch)

    @staticmethod
    def _bundle_mesh_shape(model_dir: str) -> str:
        """The bundle's CURRENT weights layout from its export manifest
        (``"unsharded"`` for legacy/flat bundles) — compared against the
        mesh the executables were compiled under, so a stale ``aot/``
        dir beside a re-sharded export falls back instead of feeding
        wrong-shape parameters to a serialized program."""
        from shifu_tensorflow_tpu.export.saved_model import NATIVE_MANIFEST

        try:
            mpath = os.path.join(model_dir, NATIVE_MANIFEST)
            if fs.exists(mpath):
                doc = json.loads(fs.read_text(mpath))
                return str(doc.get("mesh_shape") or "unsharded")
        except (OSError, ValueError):
            pass
        return "unsharded"

    @staticmethod
    def _generation_mismatch(model_dir: str, meta: dict) -> str | None:
        """Refuse executables compiled for a DIFFERENT weights
        generation (a stale ``aot/`` dir beside re-exported weights):
        the meta's stamped weights digest must match the bundle's —
        from the export manifest when one exists (one small read), else
        hashed from the weights file directly."""
        want = meta.get("weights_sha256")
        if not want:
            return None
        # lazy: saved_model imports jax at module top, and this module
        # must stay import-light for jax-free config resolution
        from shifu_tensorflow_tpu.export.saved_model import (
            NATIVE_MANIFEST,
            NATIVE_WEIGHTS,
        )

        try:
            mpath = os.path.join(model_dir, NATIVE_MANIFEST)
            if fs.exists(mpath):
                have = json.loads(fs.read_text(mpath)).get("sha256", "")
            else:
                import hashlib

                have = hashlib.sha256(fs.read_bytes(
                    os.path.join(model_dir, NATIVE_WEIGHTS))).hexdigest()
        except (OSError, ValueError) as e:
            return f"cannot establish the weights generation: {e}"
        if have != want:
            return ("executables were compiled for a different weights "
                    f"generation ({str(want)[:12]} != bundle "
                    f"{str(have)[:12]})")
        return None

    def covers(self, bucket: int) -> bool:
        """Whether the bundle promised an executable for this bucket.
        An unreadable meta promises everything: the bundle DID ship
        AOT, so a live compile there is a fallback, not the plan."""
        if self.meta is None:
            return True
        return int(bucket) in self.buckets

    def load_bucket(self, bucket: int):
        """Deserialize one bucket's executable onto the current
        backend.  Raises :class:`AotLoadError` on any failure — corrupt
        payload (size/CRC32 checked against the meta before the pickle
        layer ever sees the bytes), fingerprint mismatch, or a backend
        that refuses the deserialization."""
        if self.unusable:
            raise AotLoadError(self.unusable)
        entry = self.buckets.get(int(bucket))
        if entry is None:
            raise AotLoadError(f"bucket {bucket} not in the AOT index")
        path = os.path.join(self.model_dir, entry.get("file", ""))
        try:
            blob = fs.read_bytes(path)
        except OSError as e:
            raise AotLoadError(f"cannot read {entry.get('file')}: {e}") \
                from e
        if len(blob) != int(entry.get("size", -1)):
            raise AotLoadError(
                f"{entry.get('file')}: size {len(blob)} != recorded "
                f"{entry.get('size')}")
        if (zlib.crc32(blob) & 0xFFFFFFFF) != int(entry.get("crc32", -1)):
            raise AotLoadError(f"{entry.get('file')}: CRC32 mismatch")
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = pickle.loads(blob)
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except AotLoadError:
            raise
        except Exception as e:
            raise AotLoadError(
                f"{entry.get('file')}: deserialization failed: "
                f"{type(e).__name__}: {e}") from e


def resolve_aot_buckets(args, conf) -> tuple[int, ...] | None:
    """The export CLI's AOT decision: None when AOT export is off
    (``--export-aot`` / ``shifu.tpu.export-aot``), else the bucket
    ladder up to ``--export-aot-rows`` / ``shifu.tpu.export-aot-rows``
    — by default the same ladder the serve plane warms
    (``ladder(serve-queue-rows)``), so an exported bundle covers every
    bucket a default server's admission bound can reach."""
    enabled = getattr(args, "export_aot", None)
    if enabled is None:
        enabled = conf.get_bool(K.EXPORT_AOT, K.DEFAULT_EXPORT_AOT)
    if not enabled:
        return None
    rows = getattr(args, "export_aot_rows", None)
    if rows is None:
        rows = conf.get_int(K.EXPORT_AOT_ROWS, K.DEFAULT_EXPORT_AOT_ROWS)
    return ladder(int(rows))
