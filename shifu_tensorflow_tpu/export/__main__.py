"""Batch-scoring CLI — the eval entry point.

Parity surface: the reference's eval module plugs the exported model into
Shifu's Java batch-eval pipeline (`TensorflowModel implements Computable`,
TensorflowModel.java:32) — rows in, scores out, KS/AUC computed downstream.
Here the same operation is one command against any exported bundle:

    python -m shifu_tensorflow_tpu.export \
        --model-dir ./model-export --data-path /data/eval \
        --target-column 0 --output scores.txt

Backends: ``native`` (flax, default), ``cpp`` (the C++ scorer — DNN family,
zero Python-ML runtime), ``saved_model`` (TensorFlow — the exact signature
the Java evaluator consumes).  When the data carries a target column the
summary line includes KS and AUC.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from shifu_tensorflow_tpu.data.dataset import ShardStream
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.data.splitter import list_data_files
from shifu_tensorflow_tpu.export.eval_model import EvalModel
from shifu_tensorflow_tpu.ops import metrics as M


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shifu_tensorflow_tpu.export",
        description="Score PSV(.gz) rows against an exported model bundle.",
    )
    p.add_argument("--model-dir", required=True)
    p.add_argument("--data-path", required=True,
                   help="file/dir of delimited rows to score")
    p.add_argument("--backend", default="native",
                   choices=["native", "cpp", "saved_model"])
    p.add_argument("--feature-columns", default=None,
                   help="comma-separated; default: 1..num_features in order")
    p.add_argument("--target-column", type=int, default=None,
                   help="label column for KS/AUC (omit to skip metrics)")
    p.add_argument("--weight-column", type=int, default=None)
    p.add_argument("--delimiter", default="|")
    p.add_argument("--batch-size", type=int, default=4096)
    p.add_argument("--output", default=None,
                   help="write one score per line here (default: no file)")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # after parse_args (--help must not pay a jax import), before any
    # jax-touching work
    from shifu_tensorflow_tpu.utils.jaxenv import honor_cpu_pin

    honor_cpu_pin()
    paths = list_data_files(args.data_path)
    if not paths:
        print(f"no data files under {args.data_path}", file=sys.stderr)
        return 2

    with EvalModel(args.model_dir, backend=args.backend) as em:
        if args.feature_columns:
            features = tuple(
                int(c) for c in args.feature_columns.split(",")
            )
        else:
            # the reference layout: target first, then the feature vector
            features = tuple(range(1, em.num_features + 1))
        if len(features) != em.num_features:
            print(
                f"model expects {em.num_features} features, schema has "
                f"{len(features)}",
                file=sys.stderr,
            )
            return 2
        has_target = args.target_column is not None
        schema = RecordSchema(
            feature_columns=features,
            # scoring-only data may have no label; reuse a feature column as
            # a stand-in target so the row parser has a full wanted set
            target_column=args.target_column if has_target else features[0],
            weight_column=(
                args.weight_column if args.weight_column is not None else -1
            ),
            delimiter=args.delimiter,
        )
        stream = ShardStream(paths, schema, args.batch_size, valid_rate=0.0)
        out_f = open(args.output, "w") if args.output else None
        scores, labels, weights = [], [], []
        n_rows = 0
        try:
            for batch in stream:
                mask = batch["w"][:, 0] > 0  # padding rows carry weight 0
                x = batch["x"][mask]
                if x.shape[0] == 0:
                    continue
                s = em.compute_batch(x)[:, 0]
                n_rows += x.shape[0]
                if out_f is not None:
                    out_f.write("\n".join(f"{v:.6f}" for v in s) + "\n")
                if has_target:
                    scores.append(s)
                    labels.append(batch["y"][mask][:, 0])
                    weights.append(batch["w"][mask][:, 0])
        finally:
            if out_f is not None:
                out_f.close()

    summary = {"rows": n_rows, "backend": args.backend}
    if has_target and scores:
        s = np.concatenate(scores)
        y = np.concatenate(labels)
        w = np.concatenate(weights)
        summary["ks"] = round(float(M.ks_statistic(s, y, w)), 6)
        summary["auc"] = round(float(M.auc(s, y, w)), 6)
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
