"""ctypes binding for the C++ scorer (cpp/stpu_scorer.cc).

The zero-Python-runtime scoring path: parity with the reference's
Java→libtensorflow JNI evaluator (TensorflowModel.java:112-172) across
all four exported families (dnn, wide&deep, multi-task, and the
embedding-augmented wrapper).  ``EvalModel(backend="cpp")`` routes here;
only the sequence family raises at load (attention serving goes through
the Python/jitted scorer).
"""

from __future__ import annotations

import ctypes

import numpy as np

from shifu_tensorflow_tpu import _native

_lib = None
_checked = False


def _load():
    global _lib, _checked
    if not _checked:
        lib = _native.load("stpu_scorer")
        if lib is not None:
            try:
                lib.stpu_scorer_load.restype = ctypes.c_void_p
                lib.stpu_scorer_load.argtypes = [
                    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long,
                ]
                lib.stpu_scorer_num_features.restype = ctypes.c_long
                lib.stpu_scorer_num_features.argtypes = [ctypes.c_void_p]
                lib.stpu_scorer_num_outputs.restype = ctypes.c_long
                lib.stpu_scorer_num_outputs.argtypes = [ctypes.c_void_p]
                lib.stpu_scorer_score.restype = ctypes.c_long
                lib.stpu_scorer_score.argtypes = [
                    ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_float),
                    ctypes.c_long,
                    ctypes.POINTER(ctypes.c_float),
                ]
                lib.stpu_scorer_free.restype = None
                lib.stpu_scorer_free.argtypes = [ctypes.c_void_p]
            except AttributeError:
                lib = None
        _lib = lib
        _checked = True
    return _lib


def available() -> bool:
    return _load() is not None


class NativeScorer:
    """Owns a loaded C++ scorer handle; scores raw (un-normalized) rows —
    ZSCALE from the bundle is applied inside the native code."""

    def __init__(self, model_dir: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native scorer library unavailable")
        self._lib = lib
        err = ctypes.create_string_buffer(512)
        self._handle = lib.stpu_scorer_load(
            model_dir.encode(), err, len(err)
        )
        if not self._handle:
            raise RuntimeError(
                f"native scorer load failed: {err.value.decode()}"
            )
        self.num_features = int(lib.stpu_scorer_num_features(self._handle))
        # (n, num_outputs) scores: 1 for dnn/wide&deep, NumTasks for the
        # multi-task family
        self.num_outputs = int(lib.stpu_scorer_num_outputs(self._handle))

    def score(self, rows: np.ndarray) -> np.ndarray:
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.ndim != 2 or rows.shape[1] != self.num_features:
            raise ValueError(
                f"expected (n, {self.num_features}) rows, got {rows.shape}"
            )
        n = rows.shape[0]
        out = np.empty((n, self.num_outputs), np.float32)
        got = self._lib.stpu_scorer_score(
            self._handle,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        if got != n:
            raise RuntimeError(f"native scoring failed (returned {got})")
        return out

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.stpu_scorer_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; close() is the real contract
        try:
            self.close()
        except Exception:
            pass
