"""Pad-to-bucket batching: one compiled program per bucket, not per shape.

The jitted native scorer (export/eval_model.py) and the serving
micro-batcher (serve/batcher.py) both dispatch variable-length row
batches into a compiled XLA program.  XLA compiles per input SHAPE, so a
workload whose batch lengths vary freely — concurrent serving requests
coalesced by arrival time, the tail batch of an offline eval stream —
re-traces and re-compiles for every distinct length it ever sees
(~19 ms per trace for the flagship DNN, measured in eval_model).  Padding
every batch up to a fixed ladder of power-of-two bucket sizes bounds the
compile count at ``log2(max_bucket / min_bucket) + 1`` programs no matter
what lengths arrive; the padded rows are sliced back off the output.

This is the same lever the TensorFlow serving stack calls "batching with
allowed_batch_sizes": amortized dispatch needs shape stability, and shape
stability needs a ladder, not exact sizes.
"""

from __future__ import annotations

import os

import numpy as np

#: smallest bucket — single-row requests pad to this, so the per-row
#: Computable path and a trickle of tiny requests share ONE program
DEFAULT_MIN_BUCKET = 8
#: largest power-of-two bucket; beyond it, sizes round up to a multiple
#: of this (a fixed-batch-size offline eval loop then compiles once)
DEFAULT_MAX_BUCKET = 4096

#: drill/debug knob (env STPU_NO_BUCKET=1, or set_ladder_disabled()):
#: bucket_size() becomes the identity, deliberately re-creating the
#: classic unpadded-shape bug — every distinct batch length compiles its
#: own program.  Exists so the recompile-storm detector (obs/compile.py)
#: can be drilled end-to-end; never set it on a production fleet.
_LADDER_DISABLED = os.environ.get("STPU_NO_BUCKET", "") not in ("", "0")


def ladder_disabled() -> bool:
    return _LADDER_DISABLED


def set_ladder_disabled(disabled: bool) -> None:
    global _LADDER_DISABLED
    _LADDER_DISABLED = bool(disabled)


def bucket_size(
    n: int,
    *,
    min_bucket: int = DEFAULT_MIN_BUCKET,
    max_bucket: int = DEFAULT_MAX_BUCKET,
) -> int:
    """Smallest ladder size >= ``n``: powers of two in
    [min_bucket, max_bucket], then multiples of max_bucket above it."""
    if n < 1:
        raise ValueError(f"batch length must be >= 1, got {n}")
    if _LADDER_DISABLED:
        return n
    if n >= max_bucket:
        return ((n + max_bucket - 1) // max_bucket) * max_bucket
    b = min_bucket
    while b < n:
        b <<= 1
    return b


def ladder(
    max_rows: int,
    *,
    min_bucket: int = DEFAULT_MIN_BUCKET,
    max_bucket: int = DEFAULT_MAX_BUCKET,
) -> tuple[int, ...]:
    """Every bucket a workload bounded at ``max_rows`` rows can reach:
    the powers of two below it plus :func:`bucket_size`'s rounding of
    ``max_rows`` itself.  This is the pre-warm set — compiling exactly
    these shapes up front (``EvalModel.warm``) means no request the
    admission bound can admit ever waits on a compile."""
    if max_rows < 1:
        raise ValueError(f"max_rows must be >= 1, got {max_rows}")
    sizes = []
    b = min_bucket
    while b <= max_bucket and b < max_rows:
        sizes.append(b)
        b <<= 1
    top = bucket_size(max_rows, min_bucket=min_bucket,
                      max_bucket=max_bucket)
    if max_rows > max_bucket:
        # above the power-of-two range the ladder is EVERY multiple of
        # max_bucket up to the top — a request between two multiples
        # buckets to the intermediate one, which must be warm too
        m = 2 * max_bucket
        while m < top:
            sizes.append(m)
            m += max_bucket
    sizes.append(top)
    return tuple(sizes)


def pad_rows(rows: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``rows`` (n, f) up to (bucket, f); no-op when already
    sized.  The caller slices the first n output rows back off — padded
    rows produce scores that are never observed."""
    n = rows.shape[0]
    if n == bucket:
        return rows
    if n > bucket:
        raise ValueError(f"rows ({n}) exceed bucket ({bucket})")
    pad = np.zeros((bucket - n,) + rows.shape[1:], dtype=rows.dtype)
    return np.concatenate([rows, pad], axis=0)
