"""Serving CLI — run the scoring server against an exported bundle.

    python -m shifu_tensorflow_tpu.serve \
        --model-dir ./model-export --port 8080

Config precedence matches the training CLI: built-in defaults →
``--globalconfig`` file(s) (Hadoop XML or JSON, ``shifu.tpu.serve-*``
keys) → explicit CLI flags.  On startup the server prints one JSON line
``{"state": "listening", "port": N, ...}`` (machine-readable for smoke
tests and supervisors), serves until SIGTERM/SIGINT, then drains and
prints a final JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf
from shifu_tensorflow_tpu.serve.config import resolve_serve_config
from shifu_tensorflow_tpu.utils import retry as _retry_util


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shifu_tensorflow_tpu.serve",
        description="Serve an exported model over HTTP with micro-batched "
                    "scoring, hot reload, and shed-before-queue "
                    "backpressure.",
    )
    p.add_argument("--model-dir", default=None,
                   help="exported bundle dir (export_model output) — "
                        "single-model mode; exactly one of this and "
                        "--models-dir is required")
    p.add_argument("--models-dir", default=None, dest="models_dir",
                   help="multi-tenant mode (shifu.tpu.serve-models-dir): "
                        "every immediate subdirectory holding an export "
                        "bundle is a tenant, routed at /score/<model> "
                        "(GET /models lists them)")
    p.add_argument("--model-budget-mb", type=float, default=None,
                   dest="model_budget_mb",
                   help="admission budget in MB of bundle bytes "
                        "(shifu.tpu.serve-model-budget-mb); past it, "
                        "least-recently-used tenants evict.  0 = "
                        "unlimited")
    p.add_argument("--model-admit-wait", type=float, default=None,
                   dest="model_admit_wait",
                   help="cold-start guard seconds a request waits on an "
                        "in-flight admission before 503 + Retry-After "
                        "(shifu.tpu.serve-model-admit-wait)")
    p.add_argument("--tenant-weight", action="append", default=None,
                   dest="tenant_weight", metavar="MODEL=W",
                   help="weighted fair dispatch: device-rows weight for "
                        "one tenant (repeatable; CLI wins over "
                        "shifu.tpu.serve-tenant-weight-<model> keys)")
    p.add_argument("--globalconfig", action="append", default=[],
                   help="layered config file (XML or JSON); repeatable, "
                        "later wins")
    p.add_argument("--host", default=None)
    p.add_argument("--port", type=int, default=None,
                   help=f"0 = ephemeral (default "
                        f"{K.DEFAULT_SERVE_PORT})")
    p.add_argument("--backend", default=None,
                   choices=["native", "cpp", "saved_model"])
    p.add_argument("--max-batch", type=int, default=None, dest="max_batch",
                   help="rows per coalesced dispatch")
    p.add_argument("--max-delay-ms", type=float, default=None,
                   dest="max_delay_ms",
                   help="max wait for request coalescing")
    p.add_argument("--queue-rows", type=int, default=None, dest="queue_rows",
                   help="admission bound; beyond it requests shed with 429")
    p.add_argument("--retry-after", type=int, default=None,
                   dest="retry_after",
                   help="Retry-After seconds on shed responses")
    p.add_argument("--reload-poll-ms", type=int, default=None,
                   dest="reload_poll_ms",
                   help="export-dir poll cadence for hot reload; "
                        "0 disables")
    p.add_argument("--serve-workers", type=int, default=None,
                   dest="serve_workers",
                   help="scoring processes sharing the port via "
                        "SO_REUSEPORT (shifu.tpu.serve-workers); a parent "
                        "supervisor drains them on SIGTERM and restarts "
                        "crashes.  1 = single process (default)")
    p.add_argument("--serve-workers-max", type=int, default=None,
                   dest="serve_workers_max",
                   help="autoscaler ceiling (shifu.tpu.serve-workers-max):"
                        " with a value above --serve-workers, the "
                        "supervisor scales SO_REUSEPORT workers between "
                        "the two from the journaled SLO/shed signals "
                        "(sustained breach grows, sustained recovery "
                        "shrinks, single-tenant overload rebalances that "
                        "tenant's weight first).  Needs --obs-journal.  "
                        "0 = off (default)")
    p.add_argument("--autoscale-cooldown", type=float, default=None,
                   dest="autoscale_cooldown",
                   help="seconds the autoscaler holds still after any "
                        "decision (shifu.tpu.serve-autoscale-cooldown)")
    p.add_argument("--autoscale-poll", type=float, default=None,
                   dest="autoscale_poll",
                   help="autoscaler tick cadence in seconds "
                        "(shifu.tpu.serve-autoscale-poll)")
    p.add_argument("--supervisor-port", type=int, default=None,
                   dest="supervisor_port",
                   help="supervisor /metrics listener port "
                        "(shifu.tpu.serve-supervisor-port): scrapes "
                        "stpu_serve_scale_* gauges — live worker count, "
                        "ceiling, scale/rebalance totals, restart-budget "
                        "remaining and per-window burn.  0 = off")
    p.add_argument("--frame-port", type=int, default=None,
                   dest="frame_port",
                   help="binary wire-protocol listener "
                        "(shifu.tpu.serve-frame-port): length-prefixed "
                        "float32 frames on persistent connections, "
                        "replies multiplexed by rid — no JSON parse, no "
                        "per-row copies.  0 = off (default), -1 = "
                        "ephemeral (resolved port in the listening line)")
    p.add_argument("--frame-max-rows", type=int, default=None,
                   dest="frame_max_rows",
                   help="largest row count one frame may carry "
                        "(shifu.tpu.serve-frame-max-rows); bigger frames "
                        "get a typed 413 ERROR frame before the payload "
                        "is buffered")
    p.add_argument("--shared-lane", action="store_true", default=None,
                   dest="shared_lane",
                   help="with --serve-workers N>1, funnel every worker's "
                        "packed batches through ONE fleet-wide "
                        "DeviceScheduler on the lowest-index worker "
                        "(shifu.tpu.serve-shared-lane); siblings fall "
                        "back to private dispatch while the owner is "
                        "unreachable")
    p.add_argument("--lane-socket", default=None, dest="lane_socket",
                   help="(internal) shared-lane UNIX socket path; set by "
                        "the --serve-workers supervisor")
    p.add_argument("--no-warm", action="store_true", dest="no_warm",
                   help="skip the bucket-ladder pre-warm at startup and "
                        "on reload admits (diagnostic/benchmark arm: "
                        "exposes the first-request compile cliff)")
    p.add_argument("--worker-index", type=int, default=None,
                   dest="serve_worker_index",
                   help="(internal) index of this scoring process under "
                        "--serve-workers; set by the supervisor")
    p.add_argument("--obs-journal", default=None, dest="obs_journal",
                   help="observability journal path (shifu.tpu.obs-journal):"
                        " reload/shed lifecycle events append here; serve "
                        "workers write <path>.s<i> siblings; read "
                        "with `python -m shifu_tensorflow_tpu.obs`")
    p.add_argument("--obs-job", default=None, dest="obs_job",
                   help="(internal) fleet-wide job correlation id stamped "
                        "on journal events; set by the --serve-workers "
                        "supervisor so every worker journals the same id")
    p.add_argument("--compile-cache-dir", default=None,
                   dest="compile_cache_dir",
                   help="jax persistent compilation cache dir "
                        "(shifu.tpu.compile-cache-dir) — the middle "
                        "tier of the AOT fallback ladder: a bucket "
                        "that live-compiles (AOT mismatch, no AOT "
                        "shipped) persists its program here, so the "
                        "next worker/restart skips XLA")
    p.add_argument("--obs-baseline", default=None, dest="obs_baseline",
                   help="pinned baseline rollup (a .rollup.jsonl sidecar "
                        "or a journal base) for the cross-run regression "
                        "watchdog (shifu.tpu.obs-baseline); fires "
                        "perf_regression when live windows exceed it by "
                        "the shifu.tpu.slo-regression ratio")
    return p


def main(argv: list[str] | None = None) -> int:
    argv = list(argv) if argv is not None else sys.argv[1:]
    args = build_parser().parse_args(argv)
    # after parse_args (--help must not pay a jax import), before any
    # jax-touching work
    from shifu_tensorflow_tpu.utils.jaxenv import honor_cpu_pin

    honor_cpu_pin()
    conf = Conf()
    for path in args.globalconfig:
        conf.add_resource(path)
    _retry_util.set_default_policy(_retry_util.policy_from_conf(conf))
    try:
        config = resolve_serve_config(args, conf)
        # observability plane (shifu.tpu.obs-* / --obs-journal): the serve
        # process journals reload/shed lifecycle events beside the
        # training planes' — one fleet timeline across all three
        from shifu_tensorflow_tpu.obs import install_obs, resolve_obs_config

        obs_cfg = resolve_obs_config(args, conf)
        # job correlation id: minted once here, shared by the whole
        # serve fleet (the supervisor re-execs workers with --obs-job),
        # so the merged journal can attribute events job-wide
        import uuid as _uuid

        job_id = args.obs_job or _uuid.uuid4().hex[:8]
        needs_supervisor = (
            config.workers > 1
            # an autoscale ceiling needs the supervisor even at one
            # worker: the policy loop and the spawn/drain actuators
            # live there
            or (config.workers_max or 0) > config.workers
        )
        if needs_supervisor and args.serve_worker_index is None:
            # multi-process scale-out: this invocation becomes the
            # supervisor, each scoring process is a re-exec of this CLI
            # with --worker-index set (and the SAME argv otherwise, so
            # every knob — conf layers included — reaches the workers)
            return _supervise(argv, config, obs_cfg, job_id)
        install_obs(obs_cfg, plane="serve",
                    worker_index=args.serve_worker_index, job=job_id)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    from shifu_tensorflow_tpu.serve.model_store import ArtifactCorrupt
    from shifu_tensorflow_tpu.serve.server import ScoringServer

    try:
        server = ScoringServer(config, warm=not args.no_warm,
                               worker_index=args.serve_worker_index,
                               lane_socket=args.lane_socket)
    except (ArtifactCorrupt, ValueError) as e:
        # single-model: corrupt initial artifact fails fast; multi:
        # a missing/empty models dir does (per-tenant corruption only
        # refuses THAT tenant — the fleet still starts)
        where = config.model_dir or config.models_dir
        print(f"refusing to serve {where}: {e}", file=sys.stderr)
        return 3

    import threading

    stop = threading.Event()
    stopping: list[int] = []

    def on_signal(signum, frame):
        # only flag from the handler: HTTPServer.shutdown() BLOCKS until
        # the serve loop exits, so calling it here (on the main thread,
        # which may be the serve loop) would deadlock — the main loop
        # below does the actual teardown
        stopping.append(signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    from shifu_tensorflow_tpu.obs import journal as _obs_journal

    server.start()
    if server.multi is not None:
        admitted = server.multi.admitted()
        _obs_journal.emit("serve_start", plane="serve", port=server.port,
                          models=admitted)
        ready = {
            "state": "listening",
            "host": config.host,
            "port": server.port,
            "backend": config.backend,
            "models": sorted(server.multi.models()),
            "models_admitted": admitted,
        }
    else:
        model = server.store.current()
        _obs_journal.emit("serve_start", plane="serve", port=server.port,
                          model_epoch=model.epoch,
                          model_digest=model.digest[:12])
        ready = {
            "state": "listening",
            "host": config.host,
            "port": server.port,
            "backend": config.backend,
            "model_epoch": model.epoch,
            "model_digest": model.digest[:12],
            "model_verified": model.verified,
        }
    if args.serve_worker_index is not None:
        ready["worker_index"] = args.serve_worker_index
    if server.frame_port:
        ready["frame_port"] = server.frame_port
    print(json.dumps(ready), flush=True)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        server.close()
        counters = server.metrics.counters()
        if server.multi is not None:
            # the stopped line aggregates across tenants (the unrouted
            # surface only carries pre-resolution errors)
            for k, v in server.multi.aggregate_counters().items():
                counters[k] = counters.get(k, 0) + v
        _obs_journal.emit("serve_stop", plane="serve",
                          requests_total=counters.get("requests_total", 0),
                          shed_total=counters.get("shed_total", 0))
        print(json.dumps({
            "state": "stopped",
            "signal": stopping[0] if stopping else None,
            **{k: v for k, v in sorted(counters.items())},
        }), flush=True)
    return 0


class _Worker:
    """One supervised scoring process: the subprocess handle plus the
    reader thread that captures its stdout JSON lines (forwarded to the
    supervisor's stderr so the supervisor's OWN stdout keeps the
    one-listening-line / one-stopped-line machine-readable contract)."""

    def __init__(self, index: int, argv: list[str], port: int,
                 job_id: str | None = None):
        import subprocess
        import threading

        self.index = index
        self.listening = threading.Event()
        self.last_json: dict = {}
        # re-exec this CLI: original argv first, the supervisor's
        # overrides LAST (argparse last-wins) — the resolved port must
        # replace a possible "--port 0", the index marks the child as a
        # worker so it does not recurse into supervision, and --obs-job
        # pins the fleet-wide journal correlation id
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "shifu_tensorflow_tpu.serve", *argv,
             "--port", str(port), "--worker-index", str(index),
             *(["--obs-job", job_id] if job_id else [])],
            stdout=subprocess.PIPE,
        )
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self) -> None:
        for raw in self.proc.stdout:
            line = raw.decode(errors="replace").rstrip()
            print(f"[serve.s{self.index}] {line}", file=sys.stderr,
                  flush=True)
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                self.last_json = obj
                if obj.get("state") == "listening":
                    self.listening.set()


def _probe_port(host: str):
    """Resolve ``--port 0`` for the fleet: every worker must bind the
    SAME concrete port, so the supervisor picks an ephemeral one.  The
    probe socket is returned STILL BOUND (SO_REUSEPORT, not listening):
    closing it before the workers bind would open a window for any
    other process to take the port — held bound, the kernel reserves it,
    workers' SO_REUSEPORT binds coexist with it, and a bound
    non-listening socket receives no connections.  The caller closes it
    once every worker is listening."""
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, 0))
    except BaseException:
        s.close()
        raise
    return s, int(s.getsockname()[1])


def _start_supervisor_metrics(host: str, port: int, render):
    """Tiny /metrics-only HTTP listener on the supervisor process: the
    fleet's control-loop state (worker count, scale totals, restart-
    budget remaining + per-window burn) as stpu_serve_scale_* gauges —
    the sliding-window restart budget was previously invisible until it
    exhausted at rc 4.  Returns (server, bound_port) or (None, 0)."""
    import http.server
    import socketserver
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path != "/metrics":
                self.send_error(404)
                return
            body = render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # stdout carries the JSON contract
            pass

    class Srv(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Srv((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, int(srv.server_address[1])


def _supervise(argv: list[str], config, obs_cfg,
               job_id: str | None = None) -> int:
    """Parent of ``--serve-workers N``: spawn N scoring processes
    sharing one SO_REUSEPORT port, restart crashes (bounded), propagate
    SIGTERM as a fleet-wide drain, and aggregate the final summary.

    With ``serve-workers-max > serve-workers`` (and an obs journal) it
    ALSO runs the elastic control loop (serve/autoscale.py): the policy
    reads the fleet's own journaled SLO/shed signals and the supervisor
    applies its decisions — spawn another SO_REUSEPORT worker
    (``scale_up``), SIGTERM-drain one back (``scale_down``), or roll the
    fleet onto new ``--tenant-weight`` overrides (``rebalance``) —
    journaling every decision with its triggering evidence, so a dead
    fleet's scaling story reconstructs from the files alone."""
    import signal
    import threading
    import time as _time

    from shifu_tensorflow_tpu.obs import install_obs
    from shifu_tensorflow_tpu.obs import journal as obs_journal

    # the supervisor journals fleet lifecycle at the BASE path; workers
    # write <base>.s<i> siblings (install_obs plane="serve") stamped
    # with the same job id
    install_obs(obs_cfg, plane="serve", job=job_id)
    n = config.workers
    probe = None
    if config.port:
        port = config.port
    else:
        probe, port = _probe_port(config.host)
    # the wire-frame listener is fleet-shared too: -1 (ephemeral) must
    # resolve to ONE concrete port every worker SO_REUSEPORT-binds, so
    # the supervisor reserves it exactly like the HTTP port above
    frame_probe = None
    frame_port = config.frame_port
    if frame_port == -1:
        frame_probe, frame_port = _probe_port(config.host)
    # shared dispatch lane: the supervisor mints the fleet's UNIX socket
    # path so every spawn — initial, crash restart, scale_up, rolling
    # rebalance — agrees on it.  Worker 0 binds it (the lane owner:
    # crash restarts reuse the index and scale_down always evicts the
    # HIGHEST index, so ownership never migrates); siblings connect.
    lane_socket = None
    if config.shared_lane:
        import os as _os
        import tempfile as _tempfile

        lane_socket = _os.path.join(
            _tempfile.gettempdir(),
            f"stpu-lane-{job_id or _os.getpid()}.sock")
    # a crash loop (bad artifact, port stolen, OOM) must fail the fleet,
    # not respawn forever — but the budget is over a sliding WINDOW, not
    # the fleet's lifetime: sporadic single-worker deaths spaced hours
    # apart are transients a long-lived fleet must absorb, while a
    # crashing artifact burns through the window's budget in seconds
    restart_budget = max(5, 2 * max(n, config.workers_max or n))
    restart_window_s = 600.0
    recent_restarts: list[float] = []  # monotonic ts, pruned to window
    restarts = 0  # lifetime total, for the journal + summary only

    def budget_remaining() -> int:
        # read-only on purpose: /metrics scrapes call this from HTTP
        # threads, and a prune-by-assignment here could race the main
        # loop's append and erase a just-burned restart.  Only the main
        # loop (the sole appender) prunes.
        now = _time.monotonic()
        live = sum(1 for t in recent_restarts
                   if now - t < restart_window_s)
        return max(0, restart_budget - live)

    # ---- elastic control loop ----
    autoscale = bool(config.workers_max and config.workers_max > n)
    policy = None
    signals = None
    if autoscale:
        if not obs_cfg.journal_path:
            print(f"autoscale disabled: serve-workers-max="
                  f"{config.workers_max} needs an obs journal "
                  f"(--obs-journal) — the SLO/shed signals live there",
                  file=sys.stderr)
            autoscale = False
        else:
            from shifu_tensorflow_tpu.serve.autoscale import (
                AutoscaleConfig,
                AutoscalePolicy,
                JournalSignals,
            )

            policy = AutoscalePolicy(AutoscaleConfig(
                workers_min=n,
                workers_max=config.workers_max,
                ticks=config.autoscale_ticks,
                recovery_ticks=config.autoscale_recovery_ticks,
                cooldown_s=config.autoscale_cooldown_s,
            ))
            signals = JournalSignals(obs_cfg.journal_path)
    scale_totals = {"scale_up": 0, "scale_down": 0, "rebalance": 0}

    def worker_argv() -> list[str]:
        # the policy OWNS the weight-override state (observe() applies
        # the backoff/floor there); every spawn — scale_up, crash
        # restart, rolling rebalance — reads the one copy, so the
        # policy's view and the workers' flags cannot drift
        extra: list[str] = []
        if frame_port:
            # replaces a possible "--frame-port -1" (argparse last-wins)
            extra += ["--frame-port", str(frame_port)]
        if lane_socket:
            extra += ["--lane-socket", lane_socket]
        if policy is not None:
            for m, w in sorted(policy.weight_overrides.items()):
                # appended LAST so argparse's append-and-last-wins merge
                # lets the override beat any operator-passed weight
                extra += ["--tenant-weight", f"{m}={w:g}"]
        return [*argv, *extra]

    stop = threading.Event()
    stopping: list[int] = []

    def on_signal(signum, frame):
        stopping.append(signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    # every exit path — spawn failure, barrier failure, SIGTERM
    # mid-startup, budget exhaustion, normal drain — goes through the
    # finally below: the fleet is always reaped and the one
    # machine-readable "stopped" line always prints (a consumer tailing
    # stdout must never see a run end without it).  Spawning INSIDE the
    # try matters: if worker k's fork fails, workers 0..k-1 are already
    # listening on the shared port and must not be orphaned.
    workers: list[_Worker] = []
    expected_exits: set = set()  # _Worker objects we terminated on purpose
    # rebalance rolling restart, advanced ONE step per monitor tick (a
    # blocking roll would stall crash detection for minutes: an
    # unrelated worker dying at the start of the roll must still be
    # restarted within one poll)
    roll_queue: list[int] = []   # worker indices still to roll
    roll_in_flight: "_Worker | None" = None  # replacement warming up
    roll_old: "_Worker | None" = None        # retiring copy, still serving
    roll_deadline = 0.0
    rc: int | None = None
    drain_rc = 0
    metrics_srv = None

    def render_metrics() -> str:
        from shifu_tensorflow_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry()
        reg.set_gauge("scale_workers", len(workers))
        reg.set_gauge("scale_workers_min", n)
        reg.set_gauge("scale_workers_max", config.workers_max or n)
        reg.set_gauge("scale_autoscale_enabled", int(autoscale))
        reg.set_gauge("scale_ups_total", scale_totals["scale_up"])
        reg.set_gauge("scale_downs_total", scale_totals["scale_down"])
        reg.set_gauge("scale_rebalances_total",
                      scale_totals["rebalance"])
        if policy is not None:
            reg.set_gauge("scale_cooldown_remaining_s",
                          round(policy.cooldown_remaining_s(), 3))
        reg.set_gauge("restart_budget", restart_budget)
        reg.set_gauge("restart_budget_remaining", budget_remaining())
        reg.set_gauge("restart_budget_burn_window",
                      restart_budget - budget_remaining())
        reg.set_gauge("restarts_total", restarts)
        return reg.render_prometheus("stpu_serve_")

    def apply_decision(decision) -> None:
        nonlocal workers
        ev = {
            "reason": decision.reason,
            "workers": len(workers),
            "budget_remaining": budget_remaining(),
            **{f"evidence_{k}": v
               for k, v in decision.evidence.items()},
        }
        if decision.action == "scale_up":
            idx = min(i for i in range(len(workers) + 1)
                      if i not in {w.index for w in workers})
            w = _Worker(idx, worker_argv(), port, job_id)
            workers.append(w)
            scale_totals["scale_up"] += 1
            obs_journal.emit("scale_up", plane="serve", index=idx,
                             to_workers=len(workers), **ev)
            print(f"autoscale: scale_up -> {len(workers)} workers "
                  f"(worker {idx}; {decision.reason})", file=sys.stderr)
        elif decision.action == "scale_down":
            victim = max(workers, key=lambda w: w.index)
            workers = [w for w in workers if w is not victim]
            expected_exits.add(victim)
            if victim.proc.poll() is None:
                victim.proc.terminate()
            scale_totals["scale_down"] += 1
            obs_journal.emit("scale_down", plane="serve",
                             index=victim.index,
                             to_workers=len(workers), **ev)
            print(f"autoscale: scale_down -> {len(workers)} workers "
                  f"(drained worker {victim.index}; {decision.reason})",
                  file=sys.stderr)
        elif decision.action == "rebalance":
            # the policy already recorded the new weight in its
            # weight_overrides (the single owner worker_argv reads)
            scale_totals["rebalance"] += 1
            obs_journal.emit("rebalance", plane="serve",
                             model=decision.model,
                             weight=decision.weight, **ev)
            print(f"autoscale: rebalance tenant {decision.model} "
                  f"weight -> {decision.weight:g} (rolling restart; "
                  f"{decision.reason})", file=sys.stderr)
            # rolling restart onto the new weights: enqueued, not run
            # inline — the monitor loop advances it one worker per tick
            # (waiting for each replacement to listen before the next),
            # so crash detection keeps its 0.2s poll during the roll
            roll_queue[:] = sorted(w.index for w in workers)

    try:
        for i in range(n):
            workers.append(_Worker(i, worker_argv(), port, job_id))
        obs_journal.emit("serve_fleet_start", plane="serve", port=port,
                         workers=n, workers_max=config.workers_max or n,
                         autoscale=autoscale)
        # listening barrier: every worker up (or one dead = fail fast —
        # a fleet that can only half-listen mis-advertises its capacity)
        deadline = _time.monotonic() + 180.0
        ready = True
        for w in workers:
            while ready and not w.listening.wait(0.2):
                if stop.is_set():
                    ready = False  # drained below; signal rc wins
                elif w.proc.poll() is not None:
                    print(f"serve worker {w.index} exited rc="
                          f"{w.proc.returncode} before listening",
                          file=sys.stderr)
                    rc = 3
                    ready = False
                elif _time.monotonic() > deadline:
                    print(f"serve workers not listening after 180s",
                          file=sys.stderr)
                    rc = 3
                    ready = False
            if not ready:
                break
        if probe is not None:
            # the workers hold the port now (or the fleet is failing);
            # release the reservation either way
            probe.close()
            probe = None
        if frame_probe is not None:
            frame_probe.close()
            frame_probe = None
        if ready:
            if config.supervisor_port:
                metrics_srv, mport = _start_supervisor_metrics(
                    config.host, config.supervisor_port, render_metrics)
                print(f"[supervisor] /metrics on port {mport}",
                      file=sys.stderr)
            print(json.dumps({
                "state": "listening", "host": config.host, "port": port,
                "workers": n,
                "workers_max": config.workers_max or n,
                "autoscale": autoscale,
                **({"frame_port": frame_port} if frame_port else {}),
                **({"shared_lane": True} if lane_socket else {}),
            }), flush=True)
            next_tick = _time.monotonic() + (
                config.autoscale_poll_s if autoscale else 0.0)
            while not stop.wait(0.2):
                for i, w in enumerate(list(workers)):
                    if w.proc.poll() is None:
                        continue
                    # unprompted exit = crash (clean or not, a scoring
                    # process has no business leaving on its own)
                    obs_journal.emit("serve_worker_exit", plane="serve",
                                     index=w.index, rc=w.proc.returncode,
                                     budget_remaining=budget_remaining())
                    if budget_remaining() <= 0:
                        print(f"serve worker {w.index} died (rc="
                              f"{w.proc.returncode}) with the restart "
                              f"budget ({restart_budget} per "
                              f"{restart_window_s:.0f}s) exhausted; "
                              "stopping the fleet", file=sys.stderr)
                        rc = 4
                        stop.set()
                        break
                    restarts += 1
                    now = _time.monotonic()
                    # prune HERE, the sole appender (budget_remaining
                    # is read-only so /metrics threads can't race this)
                    recent_restarts[:] = [
                        t for t in recent_restarts
                        if now - t < restart_window_s
                    ] + [now]
                    _time.sleep(0.5)  # a crashing artifact busy-loops
                    workers[workers.index(w)] = _Worker(
                        w.index, worker_argv(), port, job_id)
                    obs_journal.emit("serve_worker_restart", plane="serve",
                                     index=w.index, restarts=restarts,
                                     budget_remaining=budget_remaining())
                    print(f"restarted serve worker {w.index} "
                          f"({restarts}/{restart_budget})", file=sys.stderr)
                # reap expected (scaled-down / rolled) workers quietly
                for w in list(expected_exits):
                    if w.proc.poll() is not None:
                        w._reader.join(timeout=5.0)
                        expected_exits.discard(w)
                # advance the rolling rebalance, one index at a time:
                # make-before-break over SO_REUSEPORT — spawn the
                # replacement on the new weights, wait for it to
                # listen, only then drain the old copy, so capacity
                # never dips mid-roll
                if roll_in_flight is not None:
                    if roll_in_flight.listening.is_set():
                        if roll_old.proc.poll() is None:
                            roll_old.proc.terminate()
                        roll_in_flight = roll_old = None
                    elif (roll_in_flight.proc.poll() is not None
                          or _time.monotonic() > roll_deadline):
                        # replacement crashed or wedged before
                        # listening: the crash path above owns its
                        # respawn (worker_argv already carries the new
                        # weights) — drain the old copy and abandon
                        # the rest of the roll rather than churn the
                        # fleet behind a broken spawn
                        print(f"rebalance roll aborted: replacement "
                              f"for worker {roll_old.index} never "
                              "listened", file=sys.stderr)
                        if roll_old.proc.poll() is None:
                            roll_old.proc.terminate()
                        roll_queue.clear()
                        roll_in_flight = roll_old = None
                if (roll_in_flight is None and roll_queue
                        and not stop.is_set()):
                    idx = roll_queue.pop(0)
                    old = next((w for w in workers if w.index == idx),
                               None)
                    if old is not None:
                        repl = _Worker(idx, worker_argv(), port, job_id)
                        workers[workers.index(old)] = repl
                        # retired but STILL SERVING until the
                        # replacement listens; the finally drain and
                        # the reap loop both know expected_exits
                        expected_exits.add(old)
                        roll_old = old
                        roll_in_flight = repl
                        roll_deadline = _time.monotonic() + 120.0
                if (autoscale and not stop.is_set()
                        and _time.monotonic() >= next_tick):
                    next_tick = (_time.monotonic()
                                 + config.autoscale_poll_s)
                    decision = policy.observe(signals.poll(),
                                              len(workers))
                    if decision is not None:
                        apply_decision(decision)
    finally:
        if probe is not None:
            probe.close()
        if frame_probe is not None:
            frame_probe.close()
        if metrics_srv is not None:
            metrics_srv.shutdown()
        # fleet-wide drain: SIGTERM each live worker (it stops
        # admitting, finishes queued dispatches, prints its summary);
        # expected exits (scale_down victims, rolled workers) drain too
        drainees = [*workers, *expected_exits]
        for w in drainees:
            if w.proc.poll() is None:
                w.proc.terminate()
        for w in drainees:
            try:
                wrc = w.proc.wait(timeout=60.0)
            except Exception:
                w.proc.kill()
                wrc = w.proc.wait()
            # wrc == -SIGTERM is OUR drain signal landing before the
            # worker installed its graceful handler (e.g. a just-
            # restarted worker still importing jax) — an expected drain
            # outcome, not a failure (and never for expected exits)
            if wrc not in (0, -signal.SIGTERM) and w not in expected_exits:
                drain_rc = drain_rc or wrc
            # the worker's final "stopped" JSON line may still be in
            # the pipe when wait() returns — let the reader drain it
            # before the aggregate summary reads last_json
            w._reader.join(timeout=10.0)
        obs_journal.emit("serve_fleet_stop", plane="serve",
                         restarts=restarts,
                         scale_ups=scale_totals["scale_up"],
                         scale_downs=scale_totals["scale_down"],
                         rebalances=scale_totals["rebalance"])
        totals: dict[str, int] = {}
        per_worker = []
        for w in workers:
            summary = (w.last_json
                       if w.last_json.get("state") == "stopped" else {})
            per_worker.append({"index": w.index, **{
                k: v for k, v in summary.items() if k != "state"}})
            for k, v in summary.items():
                if isinstance(v, (int, float)) and k != "signal":
                    totals[k] = totals.get(k, 0) + v
        stopped = {
            "state": "stopped",
            "signal": stopping[0] if stopping else None,
            "workers": len(workers) or n,
            "restarts": restarts,
            **{k: v for k, v in sorted(totals.items())},
            "per_worker": per_worker,
        }
        if any(scale_totals.values()):
            # NOTE: totals above sum the FINAL workers' counters; rolled
            # or drained workers' requests live in the journal/rollup
            # (exact monotonic counters, PR-13), not this line
            stopped["autoscale"] = dict(scale_totals)
        if lane_socket is not None:
            # the owner unlinks on clean close; a SIGKILLed owner leaves
            # the socket file behind — sweep it so the next fleet's
            # owner does not bind-fail on the stale path
            try:
                _os.unlink(lane_socket)
            except OSError:
                pass
        print(json.dumps(stopped), flush=True)
    return rc if rc is not None else (drain_rc or 0)


if __name__ == "__main__":
    sys.exit(main())
