"""Serving CLI — run the scoring server against an exported bundle.

    python -m shifu_tensorflow_tpu.serve \
        --model-dir ./model-export --port 8080

Config precedence matches the training CLI: built-in defaults →
``--globalconfig`` file(s) (Hadoop XML or JSON, ``shifu.tpu.serve-*``
keys) → explicit CLI flags.  On startup the server prints one JSON line
``{"state": "listening", "port": N, ...}`` (machine-readable for smoke
tests and supervisors), serves until SIGTERM/SIGINT, then drains and
prints a final JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.config.conf import Conf
from shifu_tensorflow_tpu.serve.config import resolve_serve_config
from shifu_tensorflow_tpu.utils import retry as _retry_util


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shifu_tensorflow_tpu.serve",
        description="Serve an exported model over HTTP with micro-batched "
                    "scoring, hot reload, and shed-before-queue "
                    "backpressure.",
    )
    p.add_argument("--model-dir", required=True,
                   help="exported bundle dir (export_model output)")
    p.add_argument("--globalconfig", action="append", default=[],
                   help="layered config file (XML or JSON); repeatable, "
                        "later wins")
    p.add_argument("--host", default=None)
    p.add_argument("--port", type=int, default=None,
                   help=f"0 = ephemeral (default "
                        f"{K.DEFAULT_SERVE_PORT})")
    p.add_argument("--backend", default=None,
                   choices=["native", "cpp", "saved_model"])
    p.add_argument("--max-batch", type=int, default=None, dest="max_batch",
                   help="rows per coalesced dispatch")
    p.add_argument("--max-delay-ms", type=float, default=None,
                   dest="max_delay_ms",
                   help="max wait for request coalescing")
    p.add_argument("--queue-rows", type=int, default=None, dest="queue_rows",
                   help="admission bound; beyond it requests shed with 429")
    p.add_argument("--retry-after", type=int, default=None,
                   dest="retry_after",
                   help="Retry-After seconds on shed responses")
    p.add_argument("--reload-poll-ms", type=int, default=None,
                   dest="reload_poll_ms",
                   help="export-dir poll cadence for hot reload; "
                        "0 disables")
    p.add_argument("--obs-journal", default=None, dest="obs_journal",
                   help="observability journal path (shifu.tpu.obs-journal):"
                        " reload/shed lifecycle events append here; read "
                        "with `python -m shifu_tensorflow_tpu.obs`")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    # after parse_args (--help must not pay a jax import), before any
    # jax-touching work
    from shifu_tensorflow_tpu.utils.jaxenv import honor_cpu_pin

    honor_cpu_pin()
    conf = Conf()
    for path in args.globalconfig:
        conf.add_resource(path)
    _retry_util.set_default_policy(_retry_util.policy_from_conf(conf))
    try:
        config = resolve_serve_config(args, conf)
        # observability plane (shifu.tpu.obs-* / --obs-journal): the serve
        # process journals reload/shed lifecycle events beside the
        # training planes' — one fleet timeline across all three
        from shifu_tensorflow_tpu.obs import install_obs, resolve_obs_config

        obs_cfg = resolve_obs_config(args, conf)
        install_obs(obs_cfg, plane="serve")
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    from shifu_tensorflow_tpu.serve.model_store import ArtifactCorrupt
    from shifu_tensorflow_tpu.serve.server import ScoringServer

    try:
        server = ScoringServer(config)
    except ArtifactCorrupt as e:
        print(f"refusing to serve {config.model_dir}: {e}", file=sys.stderr)
        return 3

    import threading

    stop = threading.Event()
    stopping: list[int] = []

    def on_signal(signum, frame):
        # only flag from the handler: HTTPServer.shutdown() BLOCKS until
        # the serve loop exits, so calling it here (on the main thread,
        # which may be the serve loop) would deadlock — the main loop
        # below does the actual teardown
        stopping.append(signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    from shifu_tensorflow_tpu.obs import journal as _obs_journal

    model = server.store.current()
    server.start()
    _obs_journal.emit("serve_start", plane="serve", port=server.port,
                      model_epoch=model.epoch,
                      model_digest=model.digest[:12])
    print(json.dumps({
        "state": "listening",
        "host": config.host,
        "port": server.port,
        "backend": config.backend,
        "model_epoch": model.epoch,
        "model_digest": model.digest[:12],
        "model_verified": model.verified,
    }), flush=True)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        server.close()
        counters = server.metrics.counters()
        _obs_journal.emit("serve_stop", plane="serve",
                          requests_total=counters.get("requests_total", 0),
                          shed_total=counters.get("shed_total", 0))
        print(json.dumps({
            "state": "stopped",
            "signal": stopping[0] if stopping else None,
            **{k: v for k, v in sorted(counters.items())},
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
