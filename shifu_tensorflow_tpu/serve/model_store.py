"""Hot model reload with verify-before-admit.

The serving process outlives any single export: training jobs keep
publishing new artifacts into the export dir, and the server must pick
them up without a restart — but NEVER serve a partially-written or
corrupt one.  The defense is the PR-2 verified-checkpoint scheme applied
at the serving boundary:

- ``export_native_bundle`` publishes a sidecar manifest
  (``shifu_tpu_export.manifest.json``: size + CRC32 + SHA-256 per file)
  LAST, after every covered file has committed via tmp+rename — so a
  manifest's presence implies a complete bundle;
- the store polls the manifest; a changed bundle digest triggers a
  reload attempt that re-reads every covered file and verifies it
  against the manifest BEFORE constructing the new scorer;
- verification failure (an ``export.at-rest`` bitflip/truncate under
  ``$STPU_FAULT_PLAN``, a torn write, a rotted disk) refuses the
  artifact: the store keeps serving the previous verified model and
  retries on the next poll — recovery is automatic when a good artifact
  lands;
- the swap is atomic (one reference assignment under a lock) and the old
  model is released only after the swap, through EvalModel's compute
  lock — an in-flight dispatch on the old model finishes before its
  state is torn down.

Transient read faults at the reload path (a flaky NFS mount, a remote
export dir) retry under utils/retry.py — the ``serve.reload`` faults
seam sits inside the retried callable, so chaos drills exercise exactly
the production retry envelope.  Corruption is NOT transient: it never
retries, it waits for a new artifact.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

from shifu_tensorflow_tpu.export.saved_model import (
    FEATURE_STATS,
    NATIVE_MANIFEST,
    NATIVE_WEIGHTS,
)
from shifu_tensorflow_tpu.obs import datastats as obs_datastats
from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.obs import memory as obs_memory
from shifu_tensorflow_tpu.obs import trace as obs_trace
from shifu_tensorflow_tpu.utils import faults, fs, logs
from shifu_tensorflow_tpu.utils import retry as retry_util
from shifu_tensorflow_tpu.utils.integrity import check_entry

log = logs.get("serve.store")


#: how long (monotonic) the SAME (mtime, content) must be observed
#: before the fingerprint cache trusts it — must exceed the filesystem's
#: mtime granularity (1 s on NFSv3/HDFS-style mounts), NOT the poll
#: interval: a fast poller could otherwise confirm twice inside one
#: timestamp granule and pin a stale sha against a same-granule
#: republish forever
_FP_CONFIRM_S = 2.5


class ArtifactCorrupt(RuntimeError):
    """The artifact on disk disagrees with its manifest (or cannot be
    loaded).  Deliberately carries no ``.code`` and subclasses none of
    the transport errors, so the retry classifier never retries it —
    corruption is cured by a new export, not by re-reading."""


class ModelNotLoaded(RuntimeError):
    """No model has been admitted yet."""


@dataclass(frozen=True)
class LoadedModel:
    model: object          # EvalModel
    digest: str            # bundle identity (weights SHA-256; "" = legacy)
    epoch: int             # reload generation: 0 initial, +1 per swap
    verified: bool         # manifest present and checked
    loaded_at: float
    fingerprint: str = ""  # change-detector value captured at load time
    #: parsed feature_stats.json (the training-distribution baseline the
    #: skew detector compares live traffic against), or None when the
    #: bundle shipped without one.  Trusted only when the manifest
    #: covered it (or the whole bundle is legacy manifest-less).
    feature_stats: dict | None = None


def _verify_manifest(model_dir: str) -> dict | None:
    """Read + check the export manifest: every covered file must match
    its recorded size/CRC32/SHA-256.  Returns the parsed manifest (with
    its fingerprint attached under ``__fingerprint__``), or None when
    absent (legacy export, written before manifests existed).  Raises
    :class:`ArtifactCorrupt` on any mismatch.

    The fingerprint's mtime is captured BEFORE the content read: if a
    newer export replaces the manifest mid-verify, the recorded
    fingerprint is the OLDER one and the next poll sees a change — the
    race fails open to a reload, never to permanent staleness."""
    mpath = os.path.join(model_dir, NATIVE_MANIFEST)
    if not fs.exists(mpath):
        return None
    try:
        mtime = fs.mtime_ns(mpath)
        manifest = json.loads(fs.read_text(mpath))
    except (OSError, ValueError) as e:
        raise ArtifactCorrupt(f"unreadable manifest: {e}") from e
    manifest["__fingerprint__"] = f"{manifest.get('sha256', '')}:{mtime}"
    for name, want in manifest.get("files", {}).items():
        path = os.path.join(model_dir, name)
        try:
            data = fs.read_bytes(path)
        except OSError as e:
            raise ArtifactCorrupt(f"{name}: cannot read: {e}") from e
        # same digest-check implementation the export WRITER used
        # (utils/integrity.py) — the two sides cannot drift
        mismatch = check_entry(data, want)
        if mismatch is not None:
            raise ArtifactCorrupt(f"{name}: {mismatch}")
    return manifest


def _aot_fields(model) -> dict:
    """``aot_loads``/``aot_fallbacks`` journal fields for an admission
    event — present only when the bundle shipped AOT executables, so
    pre-AOT event schemas stay byte-identical."""
    st = getattr(model, "aot_stats", None)
    if not isinstance(st, dict) or not st.get("shipped"):
        return {}
    return {"aot_loads": int(st.get("loads", 0)),
            "aot_fallbacks": int(st.get("fallbacks", 0))}


class ModelStore:
    """Atomic current-model reference + the background reload poller."""

    def __init__(
        self,
        model_dir: str,
        *,
        backend: str = "native",
        poll_interval_s: float = 2.0,
        metrics=None,
        retry_policy: retry_util.RetryPolicy | None = None,
        warm_buckets: tuple[int, ...] = (),
        model_name: str | None = None,
    ):
        self.model_dir = model_dir
        self.backend = backend
        self.poll_interval_s = poll_interval_s
        self.metrics = metrics
        self._retry_policy = retry_policy
        # tenant name under the multi-model store (serve/tenancy/):
        # stamped on this store's journal events and metrics context so
        # a merged fleet journal can tell WHICH model reloaded/refused
        self.model_name = model_name
        # manifest-content cache keyed by the manifest file's mtime_ns:
        # with nothing new published, each poll costs ONE stat instead
        # of a full read_text + json parse — at hundreds of tenants
        # each running its own poller, the idle-poll cost is what
        # scales.  _fp_seen is the unconfirmed candidate (mtime, fp,
        # first-seen monotonic); it promotes to the trusted cache only
        # after _FP_CONFIRM_S of stable observation (see _fingerprint).
        self._fp_cache: tuple[int, str] | None = None
        self._fp_seen: tuple[int, str, float] | None = None
        # the bucket ladder pre-compiled BEFORE a model is admitted
        # (initial load and every hot-reload swap): the first request —
        # and the first request after a reload — must never pay a
        # trace+compile.  Empty disables warming (tests, cpp backend).
        self.warm_buckets = tuple(warm_buckets)
        self._lock = threading.Lock()
        self._current: LoadedModel | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # initial load FAILS FAST on a corrupt artifact: starting a server
        # that can only 503 (or worse, serve garbage) helps nobody — the
        # operator points it at a good export instead
        self._current = self._load(epoch=0)
        self._register_baseline(self._current)
        log.info(
            "loaded model from %s (digest %s, verified=%s)",
            model_dir, self._current.digest[:12] or "<legacy>",
            self._current.verified,
        )

    # ---- loading ----
    def _load(self, epoch: int) -> LoadedModel:
        """Verify-then-load under the retry envelope; the serve.reload
        faults seam sits inside the retried callable so every re-attempt
        re-rolls, like a real flaky mount."""
        from shifu_tensorflow_tpu.export.eval_model import EvalModel

        def attempt() -> LoadedModel:
            faults.check("serve.reload")
            manifest = _verify_manifest(self.model_dir)
            legacy_fp = ""
            if manifest is None:
                # legacy fingerprint BEFORE constructing the model: a new
                # export landing during the construction must not stamp
                # ITS fingerprint onto this older model (same fail-open
                # rule the manifest path enforces at its re-verify)
                legacy_fp = self._fingerprint() or ""
                log.warning(
                    "export at %s has no manifest (legacy bundle): "
                    "integrity guarded only by the artifact parse",
                    self.model_dir,
                )
            try:
                model = EvalModel(self.model_dir, backend=self.backend)
            except Exception as e:
                # manifest verified but the load failed: the WRITER
                # produced garbage, or the bundle changed under us —
                # same refusal class either way
                raise ArtifactCorrupt(
                    f"artifact load failed: {type(e).__name__}: {e}"
                ) from e
            digest = (manifest or {}).get("sha256", "")
            fingerprint = (manifest or {}).get("__fingerprint__", "")
            if manifest is not None:
                # close the verify→load window: if the bundle changed
                # while EvalModel was reading it, the re-read manifest
                # disagrees and the load is discarded (next poll
                # reconciles); serving a mix of two bundles is exactly
                # the "partially-loaded model" this store exists to
                # prevent
                after = _verify_manifest(self.model_dir)
                if after is None or after.get("sha256") != digest:
                    model.release()
                    raise ArtifactCorrupt(
                        "bundle changed during load; discarded"
                    )
                # the fingerprint comes from the VERIFIED manifest read,
                # never a fresh disk read: a bundle landing after the
                # re-verify must not stamp ITS fingerprint onto this
                # older model, or the poll loop would skip it forever
                fingerprint = after["__fingerprint__"]
            else:
                # legacy: the pre-construction file-identity fingerprint
                fingerprint = legacy_fp
            self._warm(model)
            return LoadedModel(
                model=model,
                digest=digest,
                epoch=epoch,
                verified=manifest is not None,
                loaded_at=time.time(),
                fingerprint=fingerprint,
                feature_stats=self._load_feature_stats(manifest),
            )

        return retry_util.call(
            attempt, policy=self._retry_policy, site="serve.reload"
        )

    def _load_feature_stats(self, manifest: dict | None) -> dict | None:
        """The bundle-shipped drift baseline (feature_stats.json), read
        ONLY when the manifest vouches for it — its bytes were already
        digest-verified by ``_verify_manifest`` on this load attempt (a
        bit-flipped stats file therefore refuses admission before this
        runs).  A legacy manifest-less bundle reads it best-effort.  A
        stale orphan beside a manifest that does not cover it is
        ignored: nothing vouches for which generation it belongs to."""
        if manifest is not None and FEATURE_STATS not in manifest.get(
                "files", {}):
            return None
        path = os.path.join(self.model_dir, FEATURE_STATS)
        try:
            if not fs.exists(path):
                return None
            return json.loads(fs.read_text(path))
        except (OSError, ValueError) as e:
            log.warning("unreadable %s at %s: %s", FEATURE_STATS,
                        self.model_dir, e)
            return None

    def _register_baseline(self, loaded: "LoadedModel") -> None:
        """Hand the (re)loaded bundle's baseline to the data-drift
        monitor under this store's tenant name — serving traffic starts
        comparing against the NEW training distribution the moment the
        swap lands.  A bundle without stats still registers (live
        distribution stays observable via `obs data`; nothing can
        breach)."""
        mon = obs_datastats.active()
        if mon is None:
            return
        stats = loaded.feature_stats or {}
        mon.register(self.model_name or "default", stats.get("stats"),
                     columns=stats.get("feature_columns"))

    def _warm(self, model) -> None:
        """Compile the full bucket ladder on ``model`` BEFORE it is
        admitted (this runs on the loading thread — the poller for a hot
        reload — while the previous model keeps serving), so the
        first-request and first-request-after-reload latency cliffs
        disappear.  A model that cannot even score its warm-up batches
        is refused the same way a digest mismatch is: the previous
        verified (and already-warm) model keeps serving."""
        if not self.warm_buckets:
            return
        t0 = time.monotonic()
        try:
            with obs_trace.span("serve.warm"):
                traced = model.warm(self.warm_buckets)
        except Exception as e:
            model.release()
            raise ArtifactCorrupt(
                f"artifact failed warm-up scoring: {type(e).__name__}: {e}"
            ) from e
        aot = getattr(model, "aot_stats", None)
        aot = aot if isinstance(aot, dict) else {}
        if aot.get("shipped"):
            # admission became a deserialize, not a compile (or says
            # exactly why it didn't): the operator-facing counterpart
            # of the journal's kind=aot_load / aot_fallback events
            log.info(
                "warmed bucket ladder %s in %.0f ms (%d AOT "
                "executable(s) loaded, %d live-compile fallback(s)%s)",
                list(self.warm_buckets),
                (time.monotonic() - t0) * 1000.0,
                aot.get("loads", 0), aot.get("fallbacks", 0),
                f"; aot unusable: {aot['unusable']}"
                if aot.get("unusable") else "",
            )
        else:
            log.info(
                "warmed bucket ladder %s in %.0f ms (%d new traces)",
                list(self.warm_buckets),
                (time.monotonic() - t0) * 1000.0, traced,
            )

    def _fingerprint(self) -> str | None:
        """Cheap change detector: the manifest's bundle digest PLUS its
        mtime (so a re-export is always a new fingerprint, even when it
        re-publishes identical bytes after a refused corrupt generation),
        or the weights file's (mtime, size) for legacy manifest-less
        exports.  None when nothing readable is there (mid-publish; try
        later).

        The manifest content read is cached by ``mtime_ns``, but a
        candidate is only TRUSTED after the same (mtime, content) has
        been observed for ``_FP_CONFIRM_S`` of LOCAL MONOTONIC time: on
        a filesystem with coarse timestamp granularity two publishes in
        quick succession can share an mtime_ns with different bytes,
        and caching sooner would pin the stale sha forever — once the
        stable window exceeds the granularity, no same-granule sibling
        publish can still be coming.  Deliberately independent of the
        file server's clock (skew-proof) AND of the poll interval (a
        fast poller must not confirm twice inside one granule).  Steady
        state is one stat per poll; the cache never skips a CHANGED
        mtime."""
        mpath = os.path.join(self.model_dir, NATIVE_MANIFEST)
        try:
            if fs.exists(mpath):
                # mtime BEFORE content (same ordering as _verify_manifest):
                # a replace in between yields a stale-mtime chimera that
                # matches neither stored fingerprint — the poll then
                # attempts a reload, i.e. the race fails open
                mtime = fs.mtime_ns(mpath)
                cached = self._fp_cache
                if cached is not None and cached[0] == mtime:
                    return cached[1]
                sha = json.loads(fs.read_text(mpath)).get("sha256", "")
                fp = f"{sha}:{mtime}"
                now = time.monotonic()
                seen = self._fp_seen
                if seen is not None and seen[:2] == (mtime, fp):
                    if now - seen[2] >= _FP_CONFIRM_S:
                        self._fp_cache = (mtime, fp)
                else:
                    self._fp_seen = (mtime, fp, now)
                return fp
            wpath = os.path.join(self.model_dir, NATIVE_WEIGHTS)
            if fs.exists(wpath):
                return f"legacy:{fs.mtime_ns(wpath)}:{fs.size(wpath)}"
        except (OSError, ValueError):
            pass
        return None

    def _model_field(self) -> dict:
        """The ``model=`` journal dimension — empty in single-model mode
        so pre-tenancy event schemas stay byte-identical."""
        return {"model": self.model_name} if self.model_name else {}

    # ---- public surface ----
    def current(self) -> LoadedModel:
        with self._lock:
            if self._current is None:
                raise ModelNotLoaded(self.model_dir)
            return self._current

    def start(self) -> None:
        """Begin polling for new artifacts (no-op when the poll interval
        is 0: reload disabled)."""
        if self.poll_interval_s <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._poll_loop, name="serve-reload", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            current, self._current = self._current, None
        if current is not None:
            current.model.release()
            # the tenant's drift detector (and its stpu_data_* gauges)
            # leaves with the model — tenancy eviction closes through
            # here, and a frozen drift score for an unrouted tenant
            # would mislead the autoscaler exactly like a frozen p99
            mon = obs_datastats.active()
            if mon is not None:
                mon.unregister(self.model_name or "default")

    def _poll_loop(self) -> None:
        # the last fingerprint we refused, for LOG de-duplication only —
        # the reload is still re-attempted every poll.  Caching the
        # refusal as a skip would be wrong: a transient mount outage that
        # exhausts the retry budget surfaces as the same exception class
        # as real corruption, and skipping its fingerprint forever would
        # pin the server to a stale model after the mount recovers.
        # Re-verifying a genuinely corrupt artifact each poll costs one
        # manifest+file read per interval — cheap insurance.
        refused: str | None = None
        while not self._stop.wait(self.poll_interval_s):
            fp = None
            try:
                fp = self._fingerprint()
                cur = self.current()
                if fp is None or fp == cur.fingerprint:
                    continue
                self.reload_now()
                refused = None
            except ArtifactCorrupt as e:
                if self.metrics is not None:
                    self.metrics.inc("reload_failures_total")
                log_fn = log.debug if fp == refused else log.error
                if fp != refused:
                    # journal the refusal once per offending artifact —
                    # the per-poll re-verification stays, but the event
                    # stream should record state CHANGES, not poll ticks
                    obs_journal.emit("reload_refused", plane="serve",
                                     why=str(e), **self._model_field())
                refused = fp
                log_fn(
                    "refusing new artifact at %s (still serving epoch %d, "
                    "digest %s): %s",
                    self.model_dir, self.current().epoch,
                    self.current().digest[:12], e,
                )
            except Exception as e:  # poller must never die silently
                log.error("reload poll failed: %s: %s",
                          type(e).__name__, e)

    def reload_now(self) -> LoadedModel:
        """Synchronous verify-and-swap (the poll loop's body; exposed for
        tests and an operator endpoint).  Raises ArtifactCorrupt when the
        on-disk artifact fails verification — the previous model keeps
        serving."""
        with self._lock:
            next_epoch = (self._current.epoch + 1
                          if self._current is not None else 0)
        loaded = self._load(epoch=next_epoch)
        with self._lock:
            old, self._current = self._current, loaded
        self._register_baseline(loaded)
        if self.metrics is not None:
            self.metrics.inc("reloads_total")
        log.info("hot-reloaded model epoch %d (digest %s, verified=%s)",
                 loaded.epoch, loaded.digest[:12] or "<legacy>",
                 loaded.verified)
        obs_journal.emit("reload", plane="serve", epoch=loaded.epoch,
                         digest=loaded.digest[:12],
                         verified=loaded.verified,
                         **_aot_fields(loaded.model),
                         **self._model_field())
        if old is not None:
            # release AFTER the swap; EvalModel.release takes the compute
            # lock, so an in-flight dispatch on the old model finishes
            # first
            old.model.release()
            if self.warm_buckets:
                # post-release probe: tearing down the old model's
                # compiled executables and device params leaves
                # allocator/GC debt that would otherwise land on the
                # NEXT request (measured ~8-11 ms spikes on the first
                # post-swap dispatch).  One tiny already-compiled
                # dispatch absorbs it here, off the request path; a
                # released-model race (another reload won) is benign.
                try:
                    loaded.model.warm((min(self.warm_buckets),))
                except Exception:
                    pass
        # device-memory snapshot at the swap (obs/memory.py): a reload
        # is the single-model plane's admission/eviction rolled into
        # one transition — the journaled device_mem pair around it is
        # how a leaked old model shows up (the `other` bucket keeps the
        # released weights' bytes)
        mem = obs_memory.active()
        if mem is not None:
            try:
                models = {}
                name = self._model_field().get("model")
                models[name or "default"] = loaded.model.device_bytes()
                mem.snapshot(models=models, epoch=loaded.epoch,
                             reason="reload")
            except Exception:
                pass
        return loaded
