"""Persistent-connection streaming server + client for the frame
protocol.

One connection carries MANY concurrent requests: the reader thread
decodes frames as they arrive and hands each to its own handler thread,
so a slow dispatch never head-of-line-blocks the frames behind it;
replies are matched back by rid and may arrive in any order.  This is
the wire analogue of N keep-alive HTTP connections collapsed onto one
socket — the client pays one handshake and zero per-request framing
beyond the 22-byte header.

The same listener serves two roles:

- the public frame port (TCP, ``shifu.tpu.serve-frame-port``,
  SO_REUSEPORT-shared across a worker fleet like the HTTP port);
- the fleet dispatch lane's owner side (a UNIX domain socket — see
  :mod:`.lane`): sibling workers are just frame clients whose
  "requests" are their packed batches.

Error mapping mirrors the HTTP handler status-for-status (shed → 429 +
the jittered Retry-After, oversize → 413, cold start → 503 + hint …) so
an operator debugging either path reads one table (docs/serving.md).
"""

from __future__ import annotations

import os
import socket
import threading
import time

import numpy as np

from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.serve.batcher import (
    BatcherClosed,
    RequestTooLarge,
    ShedLoad,
)
from shifu_tensorflow_tpu.serve.model_store import ModelNotLoaded
from shifu_tensorflow_tpu.serve.tenancy.store import (
    AdmissionRefused,
    AmbiguousModel,
    ModelColdStart,
    UnknownModel,
)
from shifu_tensorflow_tpu.serve.wire import frame as wire
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve.wire")

#: per-connection bound on requests being scored at once; the reader
#: thread blocks past it, which backpressures the client through TCP —
#: flow control without an unbounded thread/queue per connection
MAX_INFLIGHT_PER_CONN = 64


class FrameServer:
    """Threaded frame listener bound to a :class:`ScoringServer`.

    ``uds_path`` binds a UNIX domain socket instead of TCP (the lane
    owner's side); ``lane=True`` journals ``lane_owner`` on start and
    routes scoring through ``handle_lane`` (device-truth counters only —
    a forwarded batch's request-level accounting already happened on the
    sibling that admitted it)."""

    def __init__(self, scoring, *, host: str = "", port: int = 0,
                 uds_path: str | None = None, max_rows: int,
                 reuseport: bool = False, lane: bool = False):
        self.scoring = scoring
        self.max_rows = max_rows
        self.lane = lane
        self.uds_path = uds_path
        if uds_path is not None:
            # a stale socket file from a dead predecessor (the
            # supervisor re-elects the owner by respawning index 0)
            # must not EADDRINUSE the re-bind
            try:
                os.unlink(uds_path)
            except OSError:
                pass
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(uds_path)
            self.port = 0
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if reuseport:
                self._sock.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEPORT, 1)
            self._sock.bind((host, port))
            self.port = int(self._sock.getsockname()[1])
        self._sock.listen(128)
        self._lock = threading.Lock()
        self._conns: set[socket.socket] = set()
        self._inflight = 0
        self._closing = False
        self._accept_thread: threading.Thread | None = None

    def start(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=("serve-lane-accept" if self.lane else "serve-frame-accept"),
            daemon=True)
        self._accept_thread.start()
        if self.lane:
            obs_journal.emit("lane_owner", plane="serve",
                             socket=self.uds_path)

    def connections(self) -> int:
        with self._lock:
            return len(self._conns)

    # ---- accept / read ----
    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            if self.uds_path is None:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             name="serve-frame-conn", daemon=True).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        sem = threading.Semaphore(MAX_INFLIGHT_PER_CONN)
        try:
            while True:
                try:
                    f = wire.read_frame(conn, max_rows=self.max_rows)
                except wire.FrameTooLarge as e:
                    # framing survived (payload consumed unbuffered):
                    # typed refusal, keep the connection
                    self._count_error()
                    self._send(conn, send_lock, wire.encode_error_reply(
                        413, str(e), rid=e.rid, tenant=e.tenant))
                    continue
                except wire.FrameProtocolError as e:
                    log.warning("frame connection dropped: %s", e)
                    return
                except OSError:
                    return
                if f is None:
                    return  # clean EOF
                if f.kind != wire.KIND_SCORE:
                    log.warning("unexpected frame kind %d from client",
                                f.kind)
                    return
                # bound in-flight handlers; blocking HERE (not spawning)
                # pushes backpressure into the client's send window
                sem.acquire()
                with self._lock:
                    self._inflight += 1
                threading.Thread(
                    target=self._handle, args=(conn, send_lock, sem, f),
                    name="serve-frame-req", daemon=True).start()
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    # ---- per-request handling ----
    def _handle(self, conn, send_lock, sem, f: wire.Frame) -> None:
        scoring = self.scoring
        try:
            reply = self._score_frame(scoring, f)
        finally:
            sem.release()
            with self._lock:
                self._inflight -= 1
        self._send(conn, send_lock, reply)

    def _count_error(self) -> None:
        m = self.scoring.metrics
        m.inc("frame_errors_total")
        m.inc("errors_total")

    def _score_frame(self, scoring, f: wire.Frame):
        from shifu_tensorflow_tpu.serve.server import (
            _BadRequest,
            resolve_rid,
        )

        tenant = f.tenant or None
        rid = resolve_rid(f.rid or None)
        m = scoring.metrics
        try:
            rows = f.matrix()
            if self.lane:
                scores, model = scoring.handle_lane(rows, rid, tenant)
            else:
                m.inc("frame_requests_total")
                m.inc("frame_rows_total", f.rows)
                resp = scoring.handle_rows(rows, rid, tenant)
                scores = np.asarray(resp["scores"], np.float64)
                model = resp.get("model", f.tenant)
            return wire.encode_scores_reply(scores, tenant=model or "",
                                            rid=f.rid)
        except ShedLoad as e:
            scoring.note_shed(rid, tenant)
            return wire.encode_error_reply(
                429, "overloaded, retry later", rid=f.rid,
                retry_after=e.retry_after_s)
        except _BadRequest as e:
            self._count_error()
            return wire.encode_error_reply(400, str(e), rid=f.rid)
        except UnknownModel as e:
            self._count_error()
            return wire.encode_error_reply(
                404, f"unknown model {e.args[0]!r}", rid=f.rid)
        except AmbiguousModel as e:
            self._count_error()
            return wire.encode_error_reply(400, str(e), rid=f.rid)
        except ModelColdStart as e:
            self._count_error()
            return wire.encode_error_reply(
                503, str(e), rid=f.rid, retry_after=e.retry_after_s)
        except RequestTooLarge as e:
            self._count_error()
            return wire.encode_error_reply(413, str(e), rid=f.rid)
        except (AdmissionRefused, BatcherClosed, ModelNotLoaded) as e:
            self._count_error()
            return wire.encode_error_reply(503, str(e), rid=f.rid)
        except TimeoutError as e:
            self._count_error()
            return wire.encode_error_reply(504, str(e), rid=f.rid)
        except Exception as e:  # noqa: BLE001 — the 500 fallback
            self._count_error()
            log.error("frame request failed: %s: %s", type(e).__name__, e)
            return wire.encode_error_reply(
                500, f"{type(e).__name__}: {e}", rid=f.rid)

    @staticmethod
    def _send(conn, send_lock, parts) -> None:
        head, payload = parts
        try:
            with send_lock:
                conn.sendall(head)
                if len(payload):
                    conn.sendall(payload)
        except OSError:
            pass  # client gone; its reader already noticed

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting, let in-flight requests finish (their batcher
        is still draining behind us), then drop the connections."""
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self.uds_path is not None:
            try:
                os.unlink(self.uds_path)
            except OSError:
                pass


class _PendingReply:
    __slots__ = ("event", "frame")

    def __init__(self):
        self.event = threading.Event()
        self.frame: wire.Frame | None = None


class FrameClient:
    """Client side of the frame protocol: one persistent connection,
    concurrent ``score`` calls multiplexed by rid (safe from many
    threads).  ``address`` is a ``(host, port)`` tuple for TCP or a
    filesystem path for a UNIX domain socket."""

    def __init__(self, address, *, connect_timeout_s: float = 10.0):
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(connect_timeout_s)
            self._sock.connect(address)
        else:
            self._sock = socket.create_connection(
                tuple(address), timeout=connect_timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._send_lock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict[str, _PendingReply] = {}
        self._n = 0
        self._tag = wire.mint_rid()[:8]
        self._dead: BaseException | None = None
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name="frame-client-reader",
                                        daemon=True)
        self._reader.start()

    def _mint(self) -> str:
        with self._plock:
            self._n += 1
            return f"{self._tag}.{self._n}"

    def _read_loop(self) -> None:
        err: BaseException = ConnectionError("frame connection closed")
        try:
            while True:
                f = wire.read_frame(self._sock)
                if f is None:
                    break
                with self._plock:
                    p = self._pending.get(f.rid)
                if p is not None:
                    p.frame = f
                    p.event.set()
        except (OSError, wire.FrameProtocolError) as e:
            err = e
        with self._plock:
            self._dead = err
            pending = list(self._pending.values())
            self._pending.clear()
        for p in pending:
            p.event.set()

    def submit(self, rows: np.ndarray, *, tenant: str = "",
               rid: str | None = None) -> tuple[str, _PendingReply]:
        """Send one score frame; returns ``(rid, pending)`` — pass the
        pending to :meth:`wait`.  Lets a driver keep many requests in
        flight on the one connection."""
        rid = rid or self._mint()
        p = _PendingReply()
        with self._plock:
            if self._dead is not None:
                raise self._dead
            self._pending[rid] = p
        head, payload = wire.encode_score_request(rows, tenant=tenant,
                                                  rid=rid)
        try:
            with self._send_lock:
                self._sock.sendall(head)
                self._sock.sendall(payload)
        except OSError:
            with self._plock:
                self._pending.pop(rid, None)
            raise
        return rid, p

    def wait(self, rid: str, p: _PendingReply,
             timeout_s: float = 30.0) -> np.ndarray:
        if not p.event.wait(timeout_s):
            with self._plock:
                self._pending.pop(rid, None)
            raise TimeoutError(f"no reply for frame {rid} "
                               f"within {timeout_s}s")
        with self._plock:
            self._pending.pop(rid, None)
        f = p.frame
        if f is None:
            raise self._dead or ConnectionError("frame connection closed")
        if f.kind == wire.KIND_ERROR:
            raise wire.FrameError(f.status, f.message(),
                                  retry_after=f.retry_after, rid=rid)
        return f.vector()

    def score(self, rows: np.ndarray, *, tenant: str = "",
              rid: str | None = None,
              timeout_s: float = 30.0) -> np.ndarray:
        """Blocking request/reply; raises :class:`wire.FrameError` on a
        typed refusal (``.status`` / ``.retry_after``)."""
        rid, p = self.submit(rows, tenant=tenant, rid=rid)
        return self.wait(rid, p, timeout_s=timeout_s)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)
