"""Fleet-shared dispatch lane — the sibling worker's side.

With ``--serve-workers N``, each SO_REUSEPORT worker used to own a
private DeviceScheduler: N workers fragment the device into N
uncoordinated batchers, so the coalescing lever (the 9.25x from
BENCH_SERVE) and the DRR tenant weights only ever saw 1/N of the
traffic.  The shared lane re-centralizes DISPATCH without
re-centralizing ingress: the lowest-index worker (the "lane owner",
re-elected deterministically by the supervisor because a crashed worker
restarts at its own index and scale-down always evicts the HIGHEST
index) listens on a UNIX domain socket; every sibling keeps admitting,
coalescing and scattering locally, but instead of dispatching its
packed batch to its private scheduler it forwards the batch — tenant
name + pre-padding row matrix, one frame — down the lane.  The owner
admits the forwarded matrix into its OWN tenant batcher, where it
coalesces with the owner's native traffic and every other sibling's
forwards: one scheduler, fleet-wide DRR, fleet-wide occupancy.  Replies
scatter back by rid.

Degradation is a fallback, never an outage: any failure to reach the
owner (not yet up, crashed, wedged) routes the batch to the sibling's
private dispatch path — strictly the pre-lane behavior — and in-flight
forwards stranded by a dead owner are re-dispatched locally, so a
killed owner loses ZERO requests.  The transitions journal as
``lane_degraded`` / ``lane_restored`` (and the owner's bind as
``lane_owner``), reconstructable from a dead fleet's files via
``obs summary``.
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np

from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.serve.batcher import RequestTooLarge, ShedLoad
from shifu_tensorflow_tpu.serve.wire import frame as wire
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve.lane")

#: forwarded batches in flight at once (across all tenants): the lane
#: analogue of the scheduler's MAX_STAGED — past it, forward() falls
#: back to private dispatch rather than queueing unbounded work behind
#: a possibly-wedged owner
MAX_INFLIGHT = 8

#: owner statuses a sibling can serve around locally (fallback) vs the
#: ones that are verdicts on the REQUEST itself (propagate to callers)
_PROPAGATE_STATUSES = (400, 404, 413, 429)


class _Forwarded:
    __slots__ = ("work", "batcher", "t0")

    def __init__(self, work, batcher):
        self.work = work
        self.batcher = batcher
        self.t0 = time.monotonic()


class LaneClient:
    """One per sibling worker process, shared by every tenant batcher
    (``MicroBatcher(lane=...)``).  All public methods are thread-safe;
    ``forward`` is called from pack threads, completion runs on the
    reader thread."""

    def __init__(self, socket_path: str, *,
                 reconnect_interval_s: float = 0.5):
        self.path = socket_path
        self._reconnect_s = reconnect_interval_s
        self._sock: socket.socket | None = None
        self._send_lock = threading.Lock()
        self._state = threading.Condition()
        self._pending: dict[str, _Forwarded] = {}
        self._n = 0
        self._tag = wire.mint_rid()[:6]
        self._sem = threading.Semaphore(MAX_INFLIGHT)
        self._closed = False
        self._had_lane = False   # connected at least once this outage-cycle
        self._forwarded = 0
        self._fallback = 0
        self._reconnects = 0
        self._connector = threading.Thread(target=self._connect_loop,
                                           name="serve-lane-connect",
                                           daemon=True)
        self._connector.start()

    # ---- connection management ----
    def _connect_loop(self) -> None:
        """Background (re)connector: the owner may bind its socket after
        this sibling starts (fleet spawn order is unordered) and is
        respawned at the same index after a crash — keep trying."""
        while True:
            with self._state:
                if self._closed:
                    return
                connected = self._sock is not None
            if not connected:
                try:
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.settimeout(2.0)
                    s.connect(self.path)
                    s.settimeout(None)
                except OSError:
                    try:
                        s.close()
                    except OSError:
                        pass
                    s = None
                if s is not None:
                    with self._state:
                        if self._closed:
                            s.close()
                            return
                        self._sock = s
                        self._had_lane = True
                        self._reconnects += 1
                    threading.Thread(target=self._read_loop, args=(s,),
                                     name="serve-lane-reader",
                                     daemon=True).start()
                    # journaled on EVERY successful (re)join, the first
                    # included — the fleet's lane membership record
                    obs_journal.emit("lane_restored", plane="serve",
                                     socket=self.path,
                                     connects=self._reconnects)
                    log.info("joined dispatch lane at %s", self.path)
            with self._state:
                if self._closed:
                    return
                self._state.wait(timeout=self._reconnect_s)

    def connected(self) -> bool:
        with self._state:
            return self._sock is not None

    def stats(self) -> dict:
        with self._state:
            return {
                "connected": self._sock is not None,
                "forwarded": self._forwarded,
                "fallback": self._fallback,
                "reconnects": self._reconnects,
            }

    # ---- forward path (pack threads) ----
    def forward(self, batcher, work) -> bool:
        """Try to send one packed batch down the lane.  True: the work
        now belongs to the lane (its reply — or a dead-owner fallback —
        will land in the batcher's scatter queue).  False: the caller
        dispatches privately."""
        with self._state:
            sock = self._sock
            if sock is None or self._closed:
                self._fallback += 1
                return False
        if not self._sem.acquire(timeout=5.0):
            # owner wedged (accepting but not replying): don't stack
            # more batches behind it
            with self._state:
                self._fallback += 1
            return False
        with self._state:
            self._n += 1
            rid = f"l{self._tag}.{self._n}"
            self._pending[rid] = _Forwarded(work, batcher)
            self._forwarded += 1
        work.queue_delay_s = time.monotonic() - min(
            p.t_enqueue for p in work.batch)
        work.via_lane = True
        head, payload = wire.encode_score_request(
            work.rows, tenant=batcher.model or "", rid=rid)
        try:
            with self._send_lock:
                sock.sendall(head)
                sock.sendall(payload)
        except OSError:
            # the disconnect path re-dispatches every pending forward
            # (this one included) through the private path — the work IS
            # handled, so still True
            self._on_disconnect(sock)
        return True

    # ---- reply path (reader thread) ----
    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                f = wire.read_frame(sock)
                if f is None:
                    break
                self._complete(f)
        except (OSError, wire.FrameProtocolError):
            pass
        self._on_disconnect(sock)

    def _complete(self, f: wire.Frame) -> None:
        with self._state:
            fwd = self._pending.pop(f.rid, None)
            if fwd is None:
                return  # late reply for a drained/fallen-back work
            self._state.notify_all()
        self._sem.release()
        work, batcher = fwd.work, fwd.batcher
        if f.kind == wire.KIND_SCORES:
            work.scores = f.vector()
            work.dispatch_s = time.monotonic() - fwd.t0
            # device-truth counters (batches/padded rows, serve_batch
            # event, cost ledger) were recorded at the OWNER's dispatch;
            # zero the local pad estimate so nothing double-counts
            work.bucket = work.n
            batcher._scatter_q.put(work)
            return
        if f.kind == wire.KIND_ERROR and f.status in _PROPAGATE_STATUSES:
            if f.status == 429:
                work.error = ShedLoad(max(1, f.retry_after), 0)
            elif f.status == 413:
                work.error = RequestTooLarge(f.message())
            else:
                work.error = RuntimeError(
                    f"lane owner refused batch: {f.status} {f.message()}")
            batcher._scatter_q.put(work)
            return
        # owner can't score right now (cold start, draining, 5xx) but
        # this sibling can: private dispatch, not an error
        log.warning("lane owner returned %d for a forwarded batch; "
                    "dispatching locally", f.status)
        with self._state:
            self._fallback += 1
        batcher._lane_fallback(work)

    def _on_disconnect(self, sock: socket.socket) -> None:
        """The owner went away: fail over every in-flight forward to
        private dispatch (zero lost requests) and journal the outage
        ONCE per connected period."""
        with self._state:
            if self._sock is not sock:
                return  # a racing caller already handled this socket
            self._sock = None
            had = self._had_lane
            self._had_lane = False
            stranded = list(self._pending.values())
            self._pending.clear()
            self._state.notify_all()
        try:
            sock.close()
        except OSError:
            pass
        for _ in stranded:
            self._sem.release()
        if had and not self._closed:
            obs_journal.emit("lane_degraded", plane="serve",
                             socket=self.path,
                             redispatched=len(stranded))
            log.warning("dispatch lane lost (%d in-flight batches "
                        "re-dispatched locally)", len(stranded))
        with self._state:
            self._fallback += len(stranded)
        for fwd in stranded:
            fwd.batcher._lane_fallback(fwd.work)

    # ---- drain / close ----
    def drain(self, batcher, timeout_s: float = 20.0) -> None:
        """Block until no forwarded batch of ``batcher`` is in flight
        (its drain sentinel must not pass its own outstanding work); on
        timeout the leftovers fail over to the private path so their
        callers still get answers."""
        deadline = time.monotonic() + timeout_s
        with self._state:
            while any(f.batcher is batcher for f in self._pending.values()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._state.wait(timeout=min(remaining, 0.5))
            leftovers = [rid for rid, f in self._pending.items()
                         if f.batcher is batcher]
            stranded = [self._pending.pop(rid) for rid in leftovers]
        for fwd in stranded:
            self._sem.release()
            fwd.batcher._lane_fallback(fwd.work)

    def close(self) -> None:
        with self._state:
            if self._closed:
                return
            self._closed = True
            sock = self._sock
            self._sock = None
            stranded = list(self._pending.values())
            self._pending.clear()
            self._state.notify_all()
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        for fwd in stranded:
            self._sem.release()
            fwd.batcher._lane_fallback(fwd.work)
        self._connector.join(timeout=5.0)
