"""Zero-copy columnar wire protocol + fleet-shared dispatch lane.

- :mod:`frame` — the length-prefixed binary frame codec (one contiguous
  little-endian float32 feature matrix per scoring request, scores and
  typed errors back by correlation id);
- :mod:`stream` — the persistent-connection streaming server (TCP or
  UDS) that multiplexes concurrent frames, and the matching client;
- :mod:`lane` — the fleet-shared dispatch lane: sibling SO_REUSEPORT
  workers forward packed batches to the lane-owner worker over a UDS so
  DRR + coalescing apply fleet-wide.
"""
