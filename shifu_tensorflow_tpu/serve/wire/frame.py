"""Length-prefixed binary frame codec for the scoring wire protocol.

The JSON path re-parses every float of every request on every hop; at
millions of users the serve fleet's ceiling is that text protocol, not
the device (ROADMAP item 4).  A frame carries the feature matrix as one
contiguous little-endian float32 payload, so ingress is a single
``recv`` into a single buffer and ``np.frombuffer`` hands the pack
stage a (rows, features) view WITHOUT per-row float parsing or
per-request concat copies — the serving analogue of the columnar
feed the reference system's batch eval plane used instead of
row-at-a-time scoring.

Layout (all integers little-endian)::

    uint32  length     bytes that FOLLOW this prefix
    ----------------------------------------------- length covers:
    4s      magic      b"STPU"
    uint8   version    1
    uint8   kind       1=SCORE request, 2=SCORES reply, 3=ERROR reply
    uint8   dtype      0=none, 1=float32, 2=float64
    uint8   tenant_len bytes of tenant (model) name, 0 = default route
    uint16  rid_len    bytes of correlation id
    uint16  status     ERROR frames: HTTP-equivalent status, else 0
    uint16  retry_after  ERROR frames: whole seconds, 0 = no hint
    uint32  rows
    uint32  features   0 on replies (scores are a vector of ``rows``)
    tenant bytes | rid bytes | payload

Payloads: a SCORE request carries ``rows * features`` float32 values
row-major; a SCORES reply carries ``rows`` float64 values (the
``round(6)`` discipline of ``_score_response`` applied, so the vector
is bit-identical to what the JSON path returns for the same rows); an
ERROR reply carries a UTF-8 message.

Concurrent requests multiplex on one connection and are matched back by
``rid`` — replies may arrive in any order (coalescing reorders
dispatches), so a client MUST NOT assume FIFO.
"""

from __future__ import annotations

import struct
import uuid

import numpy as np

MAGIC = b"STPU"
VERSION = 1

KIND_SCORE = 1   # client -> server: score this matrix
KIND_SCORES = 2  # server -> client: the score vector
KIND_ERROR = 3   # server -> client: typed refusal (status + message)

DTYPE_NONE = 0
DTYPE_F32 = 1
DTYPE_F64 = 2

_ITEMSIZE = {DTYPE_NONE: 0, DTYPE_F32: 4, DTYPE_F64: 8}

#: magic..features — everything between the length prefix and the
#: variable-length tail
HEADER = struct.Struct("<4sBBBBHHHII")
_LEN = struct.Struct("<I")

#: hard ceiling on ONE frame's wire size regardless of configuration —
#: a corrupt length prefix must never provoke a multi-GB allocation
MAX_FRAME_BYTES = 1 << 30


class FrameProtocolError(RuntimeError):
    """The byte stream is not speaking this protocol (bad magic/version
    or an inconsistent length) — unrecoverable for the connection, which
    is closed; nothing can be replied because framing itself is lost."""


class FrameTooLarge(RuntimeError):
    """A well-framed request exceeding the row/byte bound.  Framing is
    intact (the oversized payload was consumed without buffering it), so
    the server replies a typed 413 ERROR frame and keeps the
    connection."""

    def __init__(self, msg: str, rid: str = "", tenant: str = ""):
        super().__init__(msg)
        self.rid = rid
        self.tenant = tenant


class FrameError(RuntimeError):
    """Client side: the server answered an ERROR frame.  Carries the
    HTTP-equivalent status and the (jittered, on 429) Retry-After."""

    def __init__(self, status: int, message: str, retry_after: int = 0,
                 rid: str = ""):
        super().__init__(f"{status}: {message}")
        self.status = status
        self.retry_after = retry_after
        self.rid = rid


class Frame:
    """One decoded frame; ``payload`` is a memoryview over the single
    receive buffer — :meth:`matrix` / :meth:`vector` are zero-copy views
    of it."""

    __slots__ = ("kind", "dtype", "tenant", "rid", "status",
                 "retry_after", "rows", "features", "payload")

    def __init__(self, kind, dtype, tenant, rid, status, retry_after,
                 rows, features, payload):
        self.kind = kind
        self.dtype = dtype
        self.tenant = tenant
        self.rid = rid
        self.status = status
        self.retry_after = retry_after
        self.rows = rows
        self.features = features
        self.payload = payload

    def matrix(self) -> np.ndarray:
        """(rows, features) float32 view over the receive buffer — the
        array handed straight to the pack stage; no copy is made."""
        return np.frombuffer(self.payload, dtype="<f4").reshape(
            self.rows, self.features)

    def vector(self) -> np.ndarray:
        """(rows,) float64 score vector of a SCORES reply."""
        return np.frombuffer(self.payload, dtype="<f8")

    def message(self) -> str:
        """UTF-8 message of an ERROR frame."""
        return bytes(self.payload).decode("utf-8", "replace")


def mint_rid() -> str:
    return uuid.uuid4().hex[:16]


def _encode_parts(kind, dtype, tenant, rid, status, retry_after, rows,
                  features, payload):
    """(header_bytes, payload_buffer): two buffers so a large payload is
    written straight from its source array, never joined into a copy."""
    tb = tenant.encode("utf-8") if tenant else b""
    rb = rid.encode("utf-8") if rid else b""
    if len(tb) > 255:
        raise ValueError(f"tenant name too long ({len(tb)} bytes)")
    if len(rb) > 255:
        raise ValueError(f"rid too long ({len(rb)} bytes)")
    length = HEADER.size + len(tb) + len(rb) + len(payload)
    head = b"".join((
        _LEN.pack(length),
        HEADER.pack(MAGIC, VERSION, kind, dtype, len(tb), len(rb),
                    status, retry_after, rows, features),
        tb, rb,
    ))
    return head, payload


def encode_score_request(rows: np.ndarray, *, tenant: str = "",
                         rid: str = ""):
    """Frame a (n, f) float32 matrix.  The payload buffer IS the
    array's memory when it is already little-endian float32 and
    C-contiguous (the only copy-free layout the server hands the pack
    stage); anything else is converted once here, on the client."""
    x = np.ascontiguousarray(rows, dtype="<f4")
    if x.ndim != 2:
        raise ValueError(f"expected (rows, features), got shape {x.shape}")
    return _encode_parts(KIND_SCORE, DTYPE_F32, tenant, rid, 0, 0,
                         x.shape[0], x.shape[1], memoryview(x).cast("B"))


def encode_scores_reply(scores: np.ndarray, *, tenant: str = "",
                        rid: str = ""):
    v = np.ascontiguousarray(scores, dtype="<f8")
    return _encode_parts(KIND_SCORES, DTYPE_F64, tenant, rid, 0, 0,
                         v.shape[0], 0, memoryview(v).cast("B"))


def encode_error_reply(status: int, message: str, *, tenant: str = "",
                       rid: str = "", retry_after: int = 0):
    body = message.encode("utf-8")[:4096]
    return _encode_parts(KIND_ERROR, DTYPE_NONE, tenant, rid, status,
                         min(retry_after, 0xFFFF), 0, 0, body)


def _recv_exact(sock, view: memoryview) -> int:
    """Fill ``view`` from the socket; returns bytes read (short only on
    EOF)."""
    got = 0
    while got < len(view):
        n = sock.recv_into(view[got:])
        if n == 0:
            break
        got += n
    return got


def _discard(sock, remaining: int) -> None:
    """Consume ``remaining`` bytes without buffering them — keeps the
    stream framed after refusing an oversized payload."""
    chunk = bytearray(min(remaining, 1 << 16))
    while remaining > 0:
        view = memoryview(chunk)[:min(remaining, len(chunk))]
        n = _recv_exact(sock, view)
        if n < len(view):
            raise FrameProtocolError("EOF inside an oversized frame")
        remaining -= n


def read_frame(sock, *, max_rows: int | None = None) -> Frame | None:
    """Read one frame off a socket.  Returns None on a clean EOF at a
    frame boundary.  Raises :class:`FrameProtocolError` on a corrupt
    stream (caller closes the connection) or :class:`FrameTooLarge`
    when the request exceeds ``max_rows`` — framing stays intact, the
    payload having been consumed unbuffered."""
    lenbuf = bytearray(4)
    got = _recv_exact(sock, memoryview(lenbuf))
    if got == 0:
        return None
    if got < 4:
        raise FrameProtocolError("EOF inside a length prefix")
    (length,) = _LEN.unpack(lenbuf)
    if length < HEADER.size or length > MAX_FRAME_BYTES:
        raise FrameProtocolError(f"implausible frame length {length}")
    head = bytearray(HEADER.size)
    if _recv_exact(sock, memoryview(head)) < HEADER.size:
        raise FrameProtocolError("EOF inside a frame header")
    (magic, version, kind, dtype, tenant_len, rid_len, status,
     retry_after, rows, features) = HEADER.unpack(head)
    if magic != MAGIC:
        raise FrameProtocolError(f"bad magic {bytes(magic)!r}")
    if version != VERSION:
        raise FrameProtocolError(f"unsupported frame version {version}")
    if dtype not in _ITEMSIZE:
        raise FrameProtocolError(f"unknown dtype tag {dtype}")
    payload_len = length - HEADER.size - tenant_len - rid_len
    if payload_len < 0:
        raise FrameProtocolError("frame length shorter than its names")
    if kind == KIND_SCORE:
        expect = rows * features * _ITEMSIZE[dtype]
        if dtype != DTYPE_F32 or rows < 1 or features < 1 \
                or payload_len != expect:
            raise FrameProtocolError(
                f"score frame geometry mismatch: {rows}x{features} "
                f"dtype {dtype} vs {payload_len} payload bytes")
    names = bytearray(tenant_len + rid_len)
    if tenant_len + rid_len:
        if _recv_exact(sock, memoryview(names)) < len(names):
            raise FrameProtocolError("EOF inside frame names")
    tenant = names[:tenant_len].decode("utf-8", "replace")
    rid = names[tenant_len:].decode("utf-8", "replace")
    if kind == KIND_SCORE and max_rows is not None and rows > max_rows:
        _discard(sock, payload_len)
        raise FrameTooLarge(
            f"frame of {rows} rows exceeds the frame bound "
            f"({max_rows}); split it", rid=rid, tenant=tenant)
    buf = bytearray(payload_len)
    if payload_len and _recv_exact(sock, memoryview(buf)) < payload_len:
        raise FrameProtocolError("EOF inside a frame payload")
    return Frame(kind, dtype, tenant, rid, status, retry_after, rows,
                 features, memoryview(buf))
