"""Serving observability: the scoring server's ``/metrics`` surface.

The metrics primitives live in :mod:`shifu_tensorflow_tpu.obs.registry`
— the single implementation behind every scrape surface
(``LatencyHistogram`` is re-exported here for compatibility; import it
from ``obs.registry`` in new code so no third copy can appear — the
old ``coordinator/metrics_board`` re-export is gone).  This module
keeps only the serve-specific composition: which counters exist, which
gauges the batcher/store contribute at render time, and the
``stpu_serve_`` prefix.  Multi-tenant serving constructs one
``ServeMetrics`` per admitted model and renders each with a
``model="<name>"`` label (``extra_labels``).
"""

from __future__ import annotations

import time

from shifu_tensorflow_tpu.obs.registry import (  # noqa: F401  (re-export)
    LatencyHistogram,
    MetricsRegistry,
)

#: counter names, fixed up front so /metrics always exposes the full set
#: (a counter that appears only after its first event breaks dashboards)
_COUNTERS = (
    "requests_total",       # valid /score requests (incl. later shed/error)
    "rows_total",           # rows scored (excl. bucket padding)
    "batches_total",        # device dispatches by the micro-batcher
    "padded_rows_total",    # padding rows added by the bucket ladder
    "shed_total",           # requests shed with 429 (backpressure)
    "nan_rows_total",       # payload rows carrying NaN/inf (rejected 400,
                            # counted per tenant — garbage in is a data
                            # signal, not just a client error)
    "errors_total",         # requests failed with 4xx/5xx (excl. 429)
    "reloads_total",        # hot-reload swaps admitted
    "reload_failures_total",  # reload attempts refused (corrupt artifact)
    # wire protocol (serve/wire/): binary-frame ingress, counted on the
    # process surface (the _unrouted series in multi-tenant mode —
    # per-tenant requests_total still counts every routed frame)
    "frame_requests_total",  # score frames received
    "frame_rows_total",      # rows received as frames
    "frame_errors_total",    # frames answered with a typed ERROR frame
)


class ServeMetrics:
    """Thin wrapper over :class:`obs.registry.MetricsRegistry` carrying
    the serving plane's counter set and gauge conventions."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        for name in _COUNTERS:
            self.registry.counter(name)
        self.request_latency = self.registry.histogram(
            "request_latency_seconds")
        self.batch_latency = self.registry.histogram("batch_latency_seconds")
        self.started_at = time.time()

    def inc(self, name: str, n: int = 1) -> None:
        if name not in _COUNTERS:
            # the registry auto-creates counters; the serve surface is a
            # FIXED set, so a typo'd name must fail loudly (the old dict
            # raised KeyError) instead of silently forking a new series
            raise KeyError(f"unknown serve counter {name!r}")
        self.registry.inc(name, n)

    def counters(self) -> dict[str, int]:
        return self.registry.counters()

    # ---- rendering ----
    def render_prometheus(
        self,
        *,
        queue_rows: int,
        model_epoch: int,
        model_digest: str,
        model_verified: bool,
        extra_labels: str = "",
    ) -> str:
        """The /metrics body.  Gauges (queue depth, loaded-model identity)
        come from the caller — they belong to the batcher/store, and
        pulling them at render time keeps this module dependency-free.
        ``extra_labels`` (e.g. ``'model="alpha"'``) stamps the
        multi-tenant model dimension onto every series; empty keeps the
        single-model output byte-identical."""
        self.registry.set_gauge("queue_rows", queue_rows)
        # batch occupancy: useful rows as a fraction of DISPATCHED rows
        # (useful + ladder padding) — the measurement surface behind the
        # fleet-wide-coalescing gate (ROADMAP item 4: N private
        # batchers fragment the device; occupancy is where it shows).
        # 1.0 when idle: no dispatch yet means no padding waste yet.
        c = self.registry.counters()
        dispatched = c.get("rows_total", 0) + c.get("padded_rows_total", 0)
        self.registry.set_gauge(
            "occupancy",
            round(c.get("rows_total", 0) / dispatched, 6)
            if dispatched else 1.0)
        self.registry.set_gauge("model_epoch", model_epoch)
        self.registry.set_gauge("model_verified", int(model_verified))
        self.registry.set_gauge("model_info", 1,
                                labels='{digest="%s"}' % model_digest)
        self.registry.set_gauge("uptime_seconds",
                                round(time.time() - self.started_at, 3))
        return self.registry.render_prometheus("stpu_serve_",
                                               extra_labels=extra_labels)
