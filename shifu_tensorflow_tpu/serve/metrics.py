"""Serving observability: counters + latency histograms + /metrics text.

The metrics-plane primitives live with the rest of the metrics plumbing
(coordinator/metrics_board.py — ``LatencyHistogram``, EpochAggregator
style: one lock, explicit snapshots, no background machinery); this
module composes them into the serving scrape surface.

Rendered in the Prometheus text exposition format because every scrape
stack speaks it; nothing here depends on a Prometheus client library.
"""

from __future__ import annotations

import threading
import time

from shifu_tensorflow_tpu.coordinator.metrics_board import LatencyHistogram

#: counter names, fixed up front so /metrics always exposes the full set
#: (a counter that appears only after its first event breaks dashboards)
_COUNTERS = (
    "requests_total",       # valid /score requests (incl. later shed/error)
    "rows_total",           # rows scored (excl. bucket padding)
    "batches_total",        # device dispatches by the micro-batcher
    "padded_rows_total",    # padding rows added by the bucket ladder
    "shed_total",           # requests shed with 429 (backpressure)
    "errors_total",         # requests failed with 4xx/5xx (excl. 429)
    "reloads_total",        # hot-reload swaps admitted
    "reload_failures_total",  # reload attempts refused (corrupt artifact)
)


class ServeMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {name: 0 for name in _COUNTERS}
        self.request_latency = LatencyHistogram()
        self.batch_latency = LatencyHistogram()
        self.started_at = time.time()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # ---- rendering ----
    def render_prometheus(
        self,
        *,
        queue_rows: int,
        model_epoch: int,
        model_digest: str,
        model_verified: bool,
    ) -> str:
        """The /metrics body.  Gauges (queue depth, loaded-model identity)
        come from the caller — they belong to the batcher/store, and
        pulling them at render time keeps this module dependency-free."""
        lines: list[str] = []

        def counter(name: str, value: float) -> None:
            lines.append(f"# TYPE stpu_serve_{name} counter")
            lines.append(f"stpu_serve_{name} {value}")

        def gauge(name: str, value: float, labels: str = "") -> None:
            lines.append(f"# TYPE stpu_serve_{name} gauge")
            lines.append(f"stpu_serve_{name}{labels} {value}")

        for name, value in sorted(self.counters().items()):
            counter(name, value)
        gauge("queue_rows", queue_rows)
        gauge("model_epoch", model_epoch)
        gauge("model_verified", int(model_verified))
        gauge("model_info", 1, labels='{digest="%s"}' % model_digest)
        gauge("uptime_seconds", round(time.time() - self.started_at, 3))
        for hist, name in ((self.request_latency, "request_latency_seconds"),
                           (self.batch_latency, "batch_latency_seconds")):
            snap = hist.snapshot()
            lines.append(f"# TYPE stpu_serve_{name} summary")
            for q in (50, 90, 99):
                lines.append(
                    'stpu_serve_%s{quantile="0.%02d"} %g'
                    % (name, q, hist.percentile(q))
                )
            lines.append(f"stpu_serve_{name}_count {snap['count']}")
            lines.append(f"stpu_serve_{name}_sum {snap['sum']:.6f}")
        return "\n".join(lines) + "\n"
