"""Online serving subsystem: micro-batched HTTP scoring with hot model
reload and shed-before-queue backpressure.  See docs/serving.md.

Import surface is intentionally lazy-friendly: ``serve.config`` carries
no jax dependency (CLI/--help path); constructing a
:class:`~shifu_tensorflow_tpu.serve.server.ScoringServer` pulls the
scorer stack.
"""

from shifu_tensorflow_tpu.serve.config import ServeConfig, resolve_serve_config

__all__ = ["ServeConfig", "resolve_serve_config"]
