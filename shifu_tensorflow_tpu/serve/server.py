"""The HTTP scoring server — stdlib only, no new dependencies.

Endpoints:

- ``POST /score`` — body ``{"rows": [[f0, f1, ...], ...]}`` (or a single
  ``"row"``); replies ``{"scores": [...], "model_epoch": N,
  "model_digest": "..."}``.  Requests coalesce through the micro-batcher
  (serve/batcher.py) into one device dispatch; overload sheds with
  ``429`` + ``Retry-After`` before the queue can collapse latency.
- ``GET /healthz`` — liveness + loaded-model identity (including the
  ``model_verified`` flag — false for legacy manifest-less bundles);
  ``503`` until a model is loaded.
- ``GET /metrics`` — Prometheus text exposition (request/batch/shed
  counters, queue depth, p50/p90/p99 latency, loaded-model
  epoch/digest/verified).

Lifecycle: ``ScoringServer(config)`` loads and verifies the initial
artifact (failing fast on corruption), starts the hot-reload poller
(serve/model_store.py), and serves on a thread-per-connection
``ThreadingHTTPServer`` with HTTP/1.1 keep-alive.  ``close()`` drains:
stop admitting, finish queued dispatches, release the model.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from shifu_tensorflow_tpu.serve.batcher import (
    BatcherClosed,
    MicroBatcher,
    RequestTooLarge,
    ShedLoad,
)
from shifu_tensorflow_tpu.export.bucketing import ladder
from shifu_tensorflow_tpu.serve.config import ServeConfig
from shifu_tensorflow_tpu.serve.metrics import ServeMetrics
from shifu_tensorflow_tpu.serve.model_store import ModelNotLoaded, ModelStore
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve")


class _BadRequest(ValueError):
    """Client-side error → 400 with the message."""


class _ReuseportHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that binds with SO_REUSEPORT, so N scoring
    processes can listen on ONE port and the kernel load-balances
    incoming connections across them — the scale-out past one process's
    GIL (``--serve-workers``).  SO_REUSEADDR alone is not enough: it
    permits rebinding a TIME_WAIT port, not concurrent listeners."""

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover
            raise OSError(
                "SO_REUSEPORT is not available on this platform; "
                "run with --serve-workers 1"
            )
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class ScoringServer:
    def __init__(
        self,
        config: ServeConfig,
        *,
        metrics: ServeMetrics | None = None,
        warm: bool = True,
        worker_index: int | None = None,
    ):
        self.config = config
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.worker_index = worker_index
        # pre-warm set: every bucket the admission bound can admit (a
        # single request may carry up to max_queue_rows rows and is
        # never split) — compiled at startup and on every hot-reload
        # admit so no /score ever waits on a trace.  warm=False is the
        # diagnostic/benchmark arm that shows the compile cliff.
        warm_buckets = ladder(config.max_queue_rows) if warm else ()
        self.store = ModelStore(
            config.model_dir,
            backend=config.backend,
            poll_interval_s=config.reload_poll_ms / 1000.0,
            metrics=self.metrics,
            warm_buckets=warm_buckets,
        )
        self.batcher = MicroBatcher(
            self._score_once,
            max_batch=config.max_batch,
            max_delay_s=config.max_delay_ms / 1000.0,
            max_queue_rows=config.max_queue_rows,
            retry_after_s=config.retry_after_s,
            metrics=self.metrics,
        )
        handler = _make_handler(self)
        # workers > 1 means this process is ONE of several sharing the
        # port — every one of them must bind with SO_REUSEPORT
        server_cls = (_ReuseportHTTPServer if config.workers > 1
                      else ThreadingHTTPServer)
        try:
            self.httpd = server_cls(
                (config.host, config.port), handler
            )
        except BaseException:
            # e.g. EADDRINUSE: without this, the started batcher thread
            # pins the score_fn closure → store → model, leaking a full
            # model's memory per failed construction attempt
            self.batcher.close(drain=False)
            self.store.close()
            raise
        self.httpd.daemon_threads = True
        self.port = int(self.httpd.server_address[1])
        self._serve_thread: threading.Thread | None = None
        self._serving = False
        self._closed = False
        # journal shed events at most once per window: the journal
        # records STATE (we are shedding), not per-request ticks — a
        # sustained overload at thousands of 429s/s would otherwise
        # rotate the lifecycle events out of the size-capped journal
        self._last_shed_emit = 0.0

    def max_body_bytes(self) -> int:
        """Reject-before-read bound on a /score body: the admission queue
        could never hold more than max_queue_rows rows anyway, and a body
        must be fully materialized (bytes → json → numpy) before the
        row-level checks can run — so without this cap a multi-GB POST
        would blow memory long before RequestTooLarge/ShedLoad fire.
        ~40 bytes/feature is generous for JSON float text."""
        try:
            nf = self.store.current().model.num_features
        except ModelNotLoaded:
            nf = 64
        return max(1 << 20, self.config.max_queue_rows * nf * 40)

    # ---- scoring (batcher thread only) ----
    def _score_once(self, rows: np.ndarray) -> np.ndarray:
        from shifu_tensorflow_tpu.export.eval_model import ModelReleasedError

        # the hot-reload swap can release the model THIS dispatch already
        # dereferenced (swap-then-release, model_store.reload_now): the
        # typed error means "re-fetch the live model", not "fail the
        # coalesced batch".  One retry suffices — current() after a swap
        # returns the already-constructed new model.
        for attempt in (0, 1):
            model = self.store.current().model
            try:
                return model.compute_batch(rows)
            except ModelReleasedError:
                if attempt:
                    raise
        raise AssertionError("unreachable")

    # ---- lifecycle ----
    def start(self) -> None:
        """Serve in a background thread — the only lifecycle path: the
        CLI starts this and parks its main thread on a signal-settable
        event (a foreground serve_forever would deadlock the signal
        handler, which must not call the blocking shutdown() itself)."""
        self.store.start()
        self._serving = True
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http", daemon=True
        )
        self._serve_thread.start()
        log.info("scoring server listening on %s:%d (model %s)",
                 self.config.host, self.port, self.config.model_dir)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._serving:
            # shutdown() blocks on an event only serve_forever sets on
            # exit — calling it on a never-started server hangs forever
            # (the construct-then-close path, e.g. a with-body raising
            # before start())
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=30.0)
        self.batcher.close(drain=True)
        self.store.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- request handling (HTTP threads) ----
    def handle_score(self, body: bytes) -> dict:
        try:
            payload = json.loads(body)
        except ValueError as e:
            raise _BadRequest(f"invalid JSON body: {e}") from e
        if not isinstance(payload, dict):
            raise _BadRequest('body must be an object with "rows" or "row"')
        if "rows" in payload:
            raw = payload["rows"]
        elif "row" in payload:
            raw = [payload["row"]]
        else:
            raise _BadRequest('body must carry "rows" (list of rows) or "row"')
        model = self.store.current()
        try:
            rows = np.asarray(raw, dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise _BadRequest(f"rows are not numeric: {e}") from e
        if rows.ndim != 2 or rows.shape[0] == 0:
            raise _BadRequest(
                f"rows must be a non-empty 2-D array, got shape "
                f"{rows.shape}"
            )
        if rows.shape[1] != model.model.num_features:
            raise _BadRequest(
                f"model expects {model.model.num_features} features per "
                f"row, got {rows.shape[1]}"
            )
        if not np.isfinite(rows).all():
            raise _BadRequest("rows contain NaN/Inf")
        self.metrics.inc("requests_total")
        scores = self.batcher.submit(rows)
        # identity re-read AFTER scoring: a hot reload that swapped while
        # this request was queued means the dispatch scored through the
        # NEW model (the batcher fetches current() at dispatch time), and
        # stamping the pre-submit snapshot would attribute its scores to
        # the old digest.  A swap inside the dispatch-to-here window can
        # still mislabel, but the stamp now matches the scoring model in
        # every ordering the batcher can actually produce.
        model = self.store.current()
        out = (scores[:, 0] if scores.ndim == 2 and scores.shape[1] == 1
               else scores)
        return {
            "scores": np.asarray(out, np.float64).round(6).tolist(),
            "model_epoch": model.epoch,
            "model_digest": model.digest[:12],
        }

    def health(self) -> tuple[int, dict]:
        try:
            m = self.store.current()
        except ModelNotLoaded:
            return 503, {"ok": False, "error": "no model loaded"}
        out = {
            "ok": True,
            "model_epoch": m.epoch,
            "model_digest": m.digest[:12],
            "model_verified": m.verified,
            "backend": self.config.backend,
            "queue_rows": self.batcher.queued_rows(),
            "uptime_s": round(time.time() - self.metrics.started_at, 1),
        }
        if self.worker_index is not None:
            out["worker_index"] = self.worker_index
        return 200, out

    def metrics_text(self) -> str:
        try:
            m = self.store.current()
            epoch, digest, verified = m.epoch, m.digest[:12], m.verified
        except ModelNotLoaded:
            epoch, digest, verified = -1, "", False
        if self.worker_index is not None:
            # /metrics is per-process by design; under --serve-workers
            # the kernel routes a scrape to an ARBITRARY worker, so each
            # response carries which one answered
            self.metrics.registry.set_gauge("worker_index",
                                            self.worker_index)
        return self.metrics.render_prometheus(
            queue_rows=self.batcher.queued_rows(),
            model_epoch=epoch,
            model_digest=digest,
            model_verified=verified,
        )


def _make_handler(server: ScoringServer):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: a load generator reusing connections must not pay a
        # TCP handshake per request
        protocol_version = "HTTP/1.1"
        server_version = "stpu-serve"
        # headers flush and the JSON body go out as separate segments;
        # with Nagle on, the second waits for the peer's delayed ACK —
        # measured ~100 ms p50 on LOOPBACK before this flag
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # route through structured logs
            log.debug("%s " + fmt, self.client_address[0], *args)

        def _reply(self, status: int, body: bytes,
                   content_type: str = "application/json",
                   extra_headers: dict | None = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, status: int, obj: dict,
                        extra_headers: dict | None = None) -> None:
            self._reply(status, json.dumps(obj).encode("utf-8"),
                        extra_headers=extra_headers)

        def do_GET(self):
            if self.path == "/healthz":
                status, obj = server.health()
                self._reply_json(status, obj)
            elif self.path == "/metrics":
                self._reply(200, server.metrics_text().encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
            else:
                self._reply_json(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path != "/score":
                self._reply_json(404, {"error": f"unknown path {self.path}"})
                return
            try:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    server.metrics.inc("errors_total")
                    self.close_connection = True
                    self._reply_json(
                        400, {"error": "unparseable Content-Length"})
                    return
                if length < 0:
                    # a negative length would slip past the limit check
                    # and turn rfile.read(-1) into read-until-EOF — which
                    # a keep-alive client never provides, leaking this
                    # handler thread forever
                    server.metrics.inc("errors_total")
                    self.close_connection = True
                    self._reply_json(
                        400, {"error": "negative Content-Length"})
                    return
                limit = server.max_body_bytes()
                if length > limit:
                    # refuse BEFORE reading: materializing a huge body
                    # (bytes → json → numpy) would blow memory long
                    # before the row-level admission checks could fire.
                    # The unread body poisons the keep-alive stream, so
                    # the connection closes with the refusal.
                    self.close_connection = True
                    server.metrics.inc("errors_total")
                    self._reply_json(413, {
                        "error": f"body of {length} bytes exceeds the "
                                 f"{limit}-byte limit; split the request"
                    })
                    return
                body = self.rfile.read(length)
                self._reply_json(200, server.handle_score(body))
            except _BadRequest as e:
                server.metrics.inc("errors_total")
                self._reply_json(400, {"error": str(e)})
            except ShedLoad as e:
                # shed counter already bumped by the batcher.  The
                # journal gets at most one event per 5s window carrying
                # the running shed_total — the per-request volume lives
                # in the counter, the journal records the CONDITION
                # (benign race on the timestamp: a duplicate event, not
                # a flood)
                now = time.monotonic()
                if now - server._last_shed_emit > 5.0:
                    server._last_shed_emit = now
                    from shifu_tensorflow_tpu.obs import (
                        journal as obs_journal,
                    )

                    obs_journal.emit(
                        "shed", plane="serve",
                        queue_rows=server.batcher.queued_rows(),
                        shed_total=server.metrics.counters().get(
                            "shed_total", 0),
                    )
                self._reply_json(
                    429,
                    {"error": "overloaded, retry later",
                     "retry_after_s": e.retry_after_s},
                    extra_headers={"Retry-After": str(e.retry_after_s)},
                )
            except RequestTooLarge as e:
                # ONLY the batcher's admission check maps to 413: a bare
                # ValueError out of the scorer is a server-side problem
                # (e.g. a mid-flight reload changed the feature width)
                # and falls through to the 500 handler below
                server.metrics.inc("errors_total")
                self._reply_json(413, {"error": str(e)})
            except (BatcherClosed, ModelNotLoaded) as e:
                server.metrics.inc("errors_total")
                self._reply_json(503, {"error": str(e)})
            except TimeoutError as e:
                server.metrics.inc("errors_total")
                self._reply_json(504, {"error": str(e)})
            except Exception as e:
                server.metrics.inc("errors_total")
                log.error("scoring request failed: %s: %s",
                          type(e).__name__, e)
                self._reply_json(
                    500, {"error": f"{type(e).__name__}: {e}"}
                )

    return Handler
