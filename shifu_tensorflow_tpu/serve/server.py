"""The HTTP scoring server — stdlib only, no new dependencies.

Endpoints:

- ``POST /score`` — body ``{"rows": [[f0, f1, ...], ...]}`` (or a single
  ``"row"``); replies ``{"scores": [...], "model_epoch": N,
  "model_digest": "..."}``.  Requests coalesce through the micro-batcher
  (serve/batcher.py) into one device dispatch; overload sheds with
  ``429`` + ``Retry-After`` before the queue can collapse latency.
- ``GET /healthz`` — liveness + loaded-model identity (including the
  ``model_verified`` flag — false for legacy manifest-less bundles);
  ``503`` until a model is loaded.
- ``GET /metrics`` — Prometheus text exposition (request/batch/shed
  counters, queue depth, p50/p90/p99 latency, loaded-model
  epoch/digest/verified).

Lifecycle: ``ScoringServer(config)`` loads and verifies the initial
artifact (failing fast on corruption), starts the hot-reload poller
(serve/model_store.py), and serves on a thread-per-connection
``ThreadingHTTPServer`` with HTTP/1.1 keep-alive.  ``close()`` drains:
stop admitting, finish queued dispatches, release the model.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from shifu_tensorflow_tpu.serve.batcher import (
    BatcherClosed,
    MicroBatcher,
    RequestTooLarge,
    ShedLoad,
)
from collections import deque

from shifu_tensorflow_tpu.export.bucketing import ladder
from shifu_tensorflow_tpu.lifecycle import ctl as lifecycle_ctl
from shifu_tensorflow_tpu.obs import datastats as obs_datastats
from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.obs import rollup as obs_rollup
from shifu_tensorflow_tpu.obs import slo as obs_slo
from shifu_tensorflow_tpu.serve.config import ServeConfig
from shifu_tensorflow_tpu.serve.metrics import ServeMetrics
from shifu_tensorflow_tpu.serve.model_store import ModelNotLoaded, ModelStore
from shifu_tensorflow_tpu.serve.tenancy.store import (
    AdmissionRefused,
    AmbiguousModel,
    ModelColdStart,
    MultiModelStore,
    UnknownModel,
)
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve")


class _BadRequest(ValueError):
    """Client-side error → 400 with the message."""


#: characters a client-supplied X-Request-Id may keep; anything else is
#: stripped (the id is echoed into headers and journals — header
#: injection and journal garbage both die here), and an id that strips
#: to nothing (or is absent) gets a minted one.  ':' is deliberately
#: NOT kept: `obs trace` reads a `\d+:\d+` argument as worker:epoch,
#: and a colon-bearing rid would shadow that grammar.
_RID_OK = re.compile(r"[^0-9A-Za-z._-]+")
_RID_MAX = 64

#: multi-tenant path grammar: /score/<model> and /healthz/<model> — the
#: model charset matches tenancy's _NAME_OK (no dotfiles, no separators,
#: so no traversal can reach the route layer either)
_MODEL_PATH = re.compile(r"/(score|healthz)/((?!\.)[0-9A-Za-z._-]{1,64})")


def resolve_rid(inbound: str | None) -> str:
    """The request's correlation id: honor a sane inbound
    ``X-Request-Id``, else mint one.  Every response (429s included)
    echoes it, and every journal event the request touches carries it —
    the end of "which request was that?" across a fleet's merged
    journal."""
    if inbound:
        rid = _RID_OK.sub("", inbound)[:_RID_MAX]
        if rid:
            return rid
    return uuid.uuid4().hex[:16]


class _ReuseportHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that binds with SO_REUSEPORT, so N scoring
    processes can listen on ONE port and the kernel load-balances
    incoming connections across them — the scale-out past one process's
    GIL (``--serve-workers``).  SO_REUSEADDR alone is not enough: it
    permits rebinding a TIME_WAIT port, not concurrent listeners."""

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover
            raise OSError(
                "SO_REUSEPORT is not available on this platform; "
                "run with --serve-workers 1"
            )
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class ScoringServer:
    def __init__(
        self,
        config: ServeConfig,
        *,
        metrics: ServeMetrics | None = None,
        warm: bool = True,
        worker_index: int | None = None,
        lane_socket: str | None = None,
    ):
        self.config = config
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.worker_index = worker_index
        self.store: ModelStore | None = None
        self.batcher: MicroBatcher | None = None
        self.multi = None
        # fleet-shared dispatch lane (serve/wire/lane.py): the
        # supervisor hands every worker the same UDS path; the
        # LOWEST-index worker owns it (crashes respawn at the same
        # index and scale-down evicts the highest, so ownership is
        # stable and re-election is just the supervisor restarting
        # worker 0), every other worker forwards its packed batches
        # down it.  Built BEFORE the stores so their batchers are
        # constructed lane-aware.
        self.lane = None           # sibling side (LaneClient)
        self.lane_server = None    # owner side (FrameServer on the UDS)
        self.frame_server = None   # public binary-frame listener
        self.frame_port = 0
        lane_owner = bool(lane_socket) and not worker_index
        if lane_socket and not lane_owner:
            from shifu_tensorflow_tpu.serve.wire.lane import LaneClient

            self.lane = LaneClient(lane_socket)
        if config.models_dir:
            # multi-tenant mode (serve/tenancy/): named models admitted
            # under the memory budget, per-model batchers feeding the
            # shared weighted-fair device scheduler.  self.metrics stays
            # the UNROUTED surface (requests that never resolved a
            # model); per-model counters live on each tenant.
            self.multi = MultiModelStore(config, warm=warm,
                                         lane=self.lane)
        else:
            # single-model mode — the PR-3/PR-5 path, unchanged
            # pre-warm set: every bucket the admission bound can admit
            # (a single request may carry up to max_queue_rows rows and
            # is never split) — compiled at startup and on every
            # hot-reload admit so no /score ever waits on a trace.
            # warm=False is the diagnostic/benchmark arm that shows the
            # compile cliff.
            warm_buckets = ladder(config.max_queue_rows) if warm else ()
            self.store = ModelStore(
                config.model_dir,
                backend=config.backend,
                poll_interval_s=config.reload_poll_ms / 1000.0,
                metrics=self.metrics,
                warm_buckets=warm_buckets,
            )
            self.batcher = MicroBatcher(
                self._score_once,
                max_batch=config.max_batch,
                max_delay_s=config.max_delay_ms / 1000.0,
                max_queue_rows=config.max_queue_rows,
                retry_after_s=config.retry_after_s,
                metrics=self.metrics,
                lane=self.lane,
            )
        handler = _make_handler(self)
        # workers > 1 means this process is ONE of several sharing the
        # port — every one of them must bind with SO_REUSEPORT.  An
        # autoscale ceiling (workers_max > workers) means siblings may
        # JOIN later even when the floor is a single worker: the first
        # worker must bind shareable too, or every scale_up would
        # EADDRINUSE against it (and against the supervisor's held
        # port-0 probe).
        elastic = (getattr(config, "workers_max", 0) or 0) > config.workers
        server_cls = (_ReuseportHTTPServer
                      if config.workers > 1 or elastic
                      else ThreadingHTTPServer)
        self.httpd = None
        try:
            self.httpd = server_cls(
                (config.host, config.port), handler
            )
            if config.frame_port:
                # the binary-frame listener (serve/wire/): -1 binds an
                # ephemeral port, anything else the named one; shared
                # with SO_REUSEPORT across a fleet like the HTTP port
                from shifu_tensorflow_tpu.serve.wire.stream import (
                    FrameServer,
                )

                self.frame_server = FrameServer(
                    self, host=config.host,
                    port=(0 if config.frame_port == -1
                          else config.frame_port),
                    max_rows=min(config.frame_max_rows,
                                 config.max_queue_rows),
                    reuseport=config.workers > 1 or elastic,
                )
                self.frame_port = self.frame_server.port
            if lane_owner:
                from shifu_tensorflow_tpu.serve.wire.stream import (
                    FrameServer,
                )

                self.lane_server = FrameServer(
                    self, uds_path=lane_socket,
                    max_rows=config.max_queue_rows, lane=True,
                )
        except BaseException:
            # e.g. EADDRINUSE: without this, the started batcher thread
            # pins the score_fn closure → store → model, leaking a full
            # model's memory per failed construction attempt
            if self.frame_server is not None:
                self.frame_server.close(timeout_s=0.0)
            if self.httpd is not None:
                self.httpd.server_close()
            if self.batcher is not None:
                self.batcher.close(drain=False)
            if self.store is not None:
                self.store.close()
            if self.multi is not None:
                self.multi.close()
            if self.lane is not None:
                self.lane.close()
            raise
        self.httpd.daemon_threads = True
        self.port = int(self.httpd.server_address[1])
        self._serve_thread: threading.Thread | None = None
        self._serving = False
        self._closed = False
        # journal shed events at most once per window PER MODEL: the
        # journal records STATE (we are shedding), not per-request
        # ticks — a sustained overload at thousands of 429s/s would
        # otherwise rotate the lifecycle events out of the size-capped
        # journal.  Key None = the single-model plane.
        self._shed_emits: dict[str | None, float] = {}
        # SLO watchdog (obs/slo.py, installed by install_obs): the
        # request path feeds its latency digest + request/shed counters,
        # and a background tick evaluates targets → journaled
        # slo_breach/slo_recover + stpu_slo_* gauges on /metrics
        self._slo = obs_slo.active()
        self._slo_stop = threading.Event()
        self._slo_thread: threading.Thread | None = None
        # rollup counter source (obs/rollup.py): the compactor polls the
        # serve plane's MONOTONIC counters each window and records
        # deltas in the rotation-exempt sidecar — rate-limited journal
        # events (shed) can undercount, these cannot.  Registering is a
        # module-dict write; without a compactor it is never polled.
        obs_rollup.register_source("serve", self._rollup_counters)
        # lifecycle reconcile state (multi-tenant only): the controller's
        # declarative ctl.json under <models_dir>/.lifecycle is applied
        # on the SLO tick — mirror wiring, ramp split, runtime tenant
        # weights, retirements — and each convergence journals
        # lifecycle_ctl_applied with the seq reached.  Per-tenant score
        # sketches (1-wide DataSketch over emitted scores) journal as
        # score_stats on the same tick: the parent-vs-shadow divergence
        # evidence the controller's promotion gate reads.
        self._ctl_seq = 0
        self._route: tuple | None = None   # (parent, shadow, fraction)
        self._mirror: tuple | None = None  # (parent, shadow)
        self._mirror_q: deque = deque(maxlen=32)
        self._mirror_n = 0
        self._mirror_stop = threading.Event()
        self._mirror_thread: threading.Thread | None = None
        self._pending_weights: dict[str, float] = {}
        self._score_sketches: dict = {}
        self._sketch_lock = threading.Lock()

    def _rollup_counters(self) -> dict:
        """Flat monotonic counters for the rollup compactor: the
        process-wide surface (single-model totals / the multi-tenant
        unrouted surface), plus every tenant's counters keyed
        ``<counter>:<model>``."""
        out: dict[str, float] = dict(self.metrics.counters())
        if self.multi is not None:
            for name, counters in self.multi.per_tenant_counters().items():
                for k, v in counters.items():
                    out[f"{k}:{name}"] = v
        return out

    def max_body_bytes(self) -> int:
        """Reject-before-read bound on a /score body: the admission queue
        could never hold more than max_queue_rows rows anyway, and a body
        must be fully materialized (bytes → json → numpy) before the
        row-level checks can run — so without this cap a multi-GB POST
        would blow memory long before RequestTooLarge/ShedLoad fire.
        ~40 bytes/feature is generous for JSON float text.  Multi-tenant
        mode bounds on the widest ADMITTED model (resolving the target
        tenant would itself admit-on-demand — too much work before the
        length check)."""
        nf = 64
        if self.multi is not None:
            nf = max(nf, self.multi.max_num_features())
        else:
            try:
                nf = self.store.current().model.num_features
            except ModelNotLoaded:
                pass
        return max(1 << 20, self.config.max_queue_rows * nf * 40)

    # ---- scoring (batcher thread only) ----
    def _score_once(self, rows: np.ndarray) -> np.ndarray:
        from shifu_tensorflow_tpu.export.eval_model import ModelReleasedError

        # the hot-reload swap can release the model THIS dispatch already
        # dereferenced (swap-then-release, model_store.reload_now): the
        # typed error means "re-fetch the live model", not "fail the
        # coalesced batch".  One retry suffices — current() after a swap
        # returns the already-constructed new model.
        for attempt in (0, 1):
            loaded = self.store.current()
            try:
                return loaded.model.compute_batch(rows)
            except ModelReleasedError:
                if attempt:
                    raise
                # journaled WITH the ids of the requests the retry
                # touched: a trace of one of them shows its dispatch hit
                # the swap window and re-scored on the new model
                obs_journal.emit(
                    "model_released_retry", plane="serve",
                    rids=self.batcher.dispatching_rids(),
                    old_epoch=loaded.epoch,
                )
        raise AssertionError("unreachable")

    # ---- lifecycle ----
    def start(self) -> None:
        """Serve in a background thread — the only lifecycle path: the
        CLI starts this and parks its main thread on a signal-settable
        event (a foreground serve_forever would deadlock the signal
        handler, which must not call the blocking shutdown() itself)."""
        if self.store is not None:
            self.store.start()
        # multi-tenant: each admitted tenant's reload poller started at
        # its admission (MultiModelStore), nothing to start here
        self._serving = True
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="serve-http", daemon=True
        )
        self._serve_thread.start()
        if self.frame_server is not None:
            self.frame_server.start()
        if self.lane_server is not None:
            # journals lane_owner: "exactly one worker owns dispatch"
            # is reconstructable from a dead fleet's journal files
            self.lane_server.start()
        if self._slo is not None:
            self._slo_thread = threading.Thread(
                target=self._slo_loop, name="serve-slo", daemon=True
            )
            self._slo_thread.start()
        log.info("scoring server listening on %s:%d (model %s)",
                 self.config.host, self.port, self.config.model_dir)

    def _slo_loop(self) -> None:
        """Evaluate the SLO watchdog several times per window — breach
        and recovery transitions journal from HERE, autonomously, so a
        dead fleet's files still tell the story even if nobody ever
        scraped /metrics during the incident.  The same tick drives the
        device/compiler leg: the compile recorder's storm state machine
        (a recompile storm whose compiles STOPPED clears only on a
        tick) and the on-demand profiler trigger poll."""
        from shifu_tensorflow_tpu.obs import compile as obs_compile
        from shifu_tensorflow_tpu.obs import profile as obs_profile

        tick = min(5.0, max(0.2, self._slo.window_s / 8.0))
        while not self._slo_stop.wait(tick):
            try:
                self._slo.evaluate()
                rec = obs_compile.active()
                if rec is not None:
                    rec.tick()
                # data leg: live-vs-baseline skew evaluation on the same
                # tick (journals data_drift/data_drift_clear + feeds the
                # slo-data-drift target the evaluate() above judges)
                mon = obs_datastats.active()
                if mon is not None:
                    mon.evaluate()
                # long-horizon leg: the cross-run regression watchdog
                # compares the live windowed digests against the pinned
                # baseline rollup on this same tick (no-op unpinned)
                obs_rollup.tick()
                obs_profile.poll()
                # lifecycle leg (PR 18): reconcile against the
                # controller's ctl file and journal the per-tenant
                # score-distribution sketches its gates read
                if self.multi is not None:
                    self._lifecycle_tick()
                    self._emit_score_stats()
            except Exception as e:  # the watchdog must never kill serving
                log.error("slo evaluation failed: %s: %s",
                          type(e).__name__, e)

    # ---- lifecycle reconcile (SLO-tick thread; multi-tenant only) ----
    def _lifecycle_tick(self) -> None:
        """Converge on the lifecycle controller's declarative intent.
        A missing/torn ctl file changes nothing; an unchanged seq costs
        one stat+read.  Weight intents for tenants not yet admitted stay
        pending and re-apply each tick (and right after the mirror pump
        admits the shadow), so the controller's weight survives the
        shadow's on-demand admission ordering."""
        doc = lifecycle_ctl.read_ctl(self.config.models_dir)
        if doc is not None and int(doc.get("seq", 0)) != self._ctl_seq:
            seq = int(doc["seq"])
            shadow = doc.get("shadow") or None
            parent = str(doc.get("model") or "")
            self._pending_weights.update({
                str(n): float(w)
                for n, w in (doc.get("weights") or {}).items()})
            if shadow and doc.get("mirror"):
                self._mirror = (parent, shadow)
                if self._mirror_thread is None:
                    self._mirror_thread = threading.Thread(
                        target=self._mirror_loop, name="serve-mirror",
                        daemon=True)
                    self._mirror_thread.start()
            else:
                self._mirror = None
                self._mirror_q.clear()
            fraction = float(doc.get("route_fraction") or 0.0)
            self._route = ((parent, shadow, fraction)
                           if shadow and fraction > 0.0 else None)
            for name in doc.get("retire") or ():
                try:
                    self.multi.retire(str(name))
                except Exception as e:
                    log.warning("lifecycle retire of %s failed: %s",
                                name, e)
                self._pending_weights.pop(str(name), None)
                with self._sketch_lock:
                    self._score_sketches.pop(str(name), None)
            self._ctl_seq = seq
            obs_journal.emit(
                "lifecycle_ctl_applied", plane="serve", seq=seq,
                shadow=shadow, mirror=bool(doc.get("mirror")),
                route_fraction=fraction,
                weights=dict(doc.get("weights") or {}),
                retire=list(doc.get("retire") or ()),
            )
        self._apply_pending_weights()

    def _apply_pending_weights(self) -> None:
        for name in list(self._pending_weights):
            try:
                self.multi.scheduler.set_weight(
                    name, self._pending_weights[name])
            except KeyError:
                continue  # not admitted yet; retry next tick
            except Exception as e:
                log.warning("lifecycle weight for %s failed: %s", name, e)
            self._pending_weights.pop(name, None)

    def _note_scores(self, model: str, scores) -> None:
        """Fold one response's scores into the tenant's cumulative
        1-wide sketch — the raw material of the score_stats events the
        lifecycle divergence gate compares."""
        if self.multi is None or scores is None:
            return
        try:
            col = np.asarray(scores, np.float64).reshape(-1, 1)
        except Exception:
            return
        with self._sketch_lock:
            sk = self._score_sketches.get(model)
            if sk is None:
                sk = obs_datastats.DataSketch(1)
                self._score_sketches[model] = sk
        sk.add_batch(col)

    def _emit_score_stats(self) -> None:
        with self._sketch_lock:
            sketches = list(self._score_sketches.items())
        for model, sk in sketches:
            snap = sk.snapshot()
            if snap:
                obs_journal.emit("score_stats", plane="serve",
                                 model=model, snapshot=snap)

    def _mirror_loop(self) -> None:
        """Drain mirrored parent rows onto the shadow tenant's batcher.
        Strictly best-effort: the queue is bounded and drop-on-full (a
        slow shadow backs nothing up into the serving path), a shed or
        cold-start on the shadow drops the sample, and NO failure here
        can surface to a client — the mirror exists to manufacture
        comparison evidence, not to serve."""
        while not self._mirror_stop.is_set():
            mirror = self._mirror
            if mirror is None or not self._mirror_q:
                if self._mirror_stop.wait(0.05):
                    return
                continue
            try:
                rows = self._mirror_q.popleft()
            except IndexError:
                continue
            _parent, shadow = mirror
            self._mirror_n += 1
            try:
                tenant = self.multi.acquire(shadow)
                self._apply_pending_weights()
                batcher = tenant.batcher
                if batcher is None:
                    continue
                scores = batcher.submit(
                    rows, rid=f"mirror-{self._mirror_n}")
                self._note_scores(shadow, scores)
            except Exception:
                continue

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._mirror_stop.set()
        if self._mirror_thread is not None:
            self._mirror_thread.join(timeout=10.0)
        # flush the compactor BEFORE unregistering: the final counter
        # deltas since the last window must land in the sidecar (the
        # conservation gate), and after this server is gone its source
        # must stop pinning the whole object graph (metrics, store,
        # model arrays) for process lifetime
        comp = obs_rollup.active()
        if comp is not None:
            comp.flush()
        obs_rollup.unregister_source("serve")
        # stop frame ingress first (public frames, then the lane's
        # sibling forwards): both wait for in-flight requests, whose
        # batchers are still alive until the drain below
        if self.frame_server is not None:
            self.frame_server.close()
        if self.lane_server is not None:
            self.lane_server.close()
        if self._serving:
            # shutdown() blocks on an event only serve_forever sets on
            # exit — calling it on a never-started server hangs forever
            # (the construct-then-close path, e.g. a with-body raising
            # before start())
            self.httpd.shutdown()
        self.httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=30.0)
        self._slo_stop.set()
        if self._slo_thread is not None:
            self._slo_thread.join(timeout=10.0)
        if self.batcher is not None:
            self.batcher.close(drain=True)
        if self.store is not None:
            self.store.close()
        if self.multi is not None:
            self.multi.close()
        if self.lane is not None:
            # after the batchers: their drain needed the lane to finish
            # (or fail over) every outstanding forward
            self.lane.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- request handling (HTTP threads) ----
    def note_shed(self, rid: str | None, model: str | None = None) -> None:
        """Bookkeep one shed refusal: watchdog counters always (plane
        AND per-tenant), journal at most once per 5s window per model
        (the journal records the CONDITION, not per-request ticks) —
        that one event carries the triggering request's id so a trace
        of a shed request can still find it."""
        t = None
        if self.multi is not None:
            # a legacy /score shed still names the tenant whose batcher
            # shed it (the unambiguous one) — the journaled CONDITION
            # must carry the real per-model counters, not the unrouted
            # surface's permanent zeros
            t = self.multi.peek(model) if model else self.multi.sole()
            if t is not None:
                model = t.name
        if self._slo is not None:
            self._slo.count("shed")
            if model:
                self._slo.count(f"shed:{model}")
        now = time.monotonic()
        if now - self._shed_emits.get(model, 0.0) <= 5.0:
            return
        self._shed_emits[model] = now
        if self.multi is not None:
            batcher = t.batcher if t is not None else None
            metrics = t.metrics if t is not None else None
            extra = {"model": model} if model else {}
            obs_journal.emit(
                "shed", plane="serve", rid=rid,
                queue_rows=(batcher.queued_rows()
                            if batcher is not None else 0),
                shed_total=(metrics.counters().get("shed_total", 0)
                            if metrics is not None else 0),
                **extra,
            )
        else:
            obs_journal.emit(
                "shed", plane="serve", rid=rid,
                queue_rows=self.batcher.queued_rows(),
                shed_total=self.metrics.counters().get("shed_total", 0),
            )

    @staticmethod
    def _parse_raw(body: bytes):
        try:
            payload = json.loads(body)
        except ValueError as e:
            raise _BadRequest(f"invalid JSON body: {e}") from e
        if not isinstance(payload, dict):
            raise _BadRequest('body must be an object with "rows" or "row"')
        if "rows" in payload:
            return payload["rows"]
        if "row" in payload:
            return [payload["row"]]
        raise _BadRequest('body must carry "rows" (list of rows) or "row"')

    @staticmethod
    def _to_rows(raw, num_features: int) -> np.ndarray:
        try:
            rows = np.asarray(raw, dtype=np.float32)
        except (TypeError, ValueError) as e:
            raise _BadRequest(f"rows are not numeric: {e}") from e
        if rows.ndim != 2 or rows.shape[0] == 0:
            raise _BadRequest(
                f"rows must be a non-empty 2-D array, got shape "
                f"{rows.shape}"
            )
        if rows.shape[1] != num_features:
            raise _BadRequest(
                f"model expects {num_features} features per "
                f"row, got {rows.shape[1]}"
            )
        return rows

    @staticmethod
    def _reject_nonfinite(rows: np.ndarray, metrics,
                          model: str | None) -> None:
        """NaN/inf payload rows are still a client error (400, as
        always) — but now a COUNTED one: ``stpu_serve_nan_rows_total``
        per tenant (satellite of the data-obs leg; a client whose
        upstream feature join broke sends NaN at scale, and a counter
        is how the operator notices before the client does), and the
        offending rows feed the tenant's live data sketch so the
        missing-rate drift signal sees traffic the scorer refused."""
        finite_rows = int(np.isfinite(rows).all(axis=1).sum())
        bad = rows.shape[0] - finite_rows
        if not bad:
            return
        if metrics is not None:
            metrics.inc("nan_rows_total", bad)
        mon = obs_datastats.active()
        if mon is not None:
            mon.observe(model or "default", rows)
        raise _BadRequest("rows contain NaN/Inf")

    @staticmethod
    def _score_response(scores: np.ndarray, loaded, rid: str | None,
                        model: str | None = None) -> dict:
        """The /score response body, shared by the single-model and
        multi-tenant paths so rounding and identity stamping can never
        diverge between them."""
        out = (scores[:, 0] if scores.ndim == 2 and scores.shape[1] == 1
               else scores)
        resp: dict = {
            "scores": np.asarray(out, np.float64).round(6).tolist(),
        }
        if model is not None:
            resp["model"] = model
        resp["model_epoch"] = loaded.epoch
        resp["model_digest"] = loaded.digest[:12]
        if rid is not None:
            resp["request_id"] = rid
        return resp

    def handle_score(self, body: bytes, rid: str | None = None,
                     model_name: str | None = None) -> dict:
        return self.handle_rows(self._parse_raw(body), rid, model_name)

    def handle_rows(self, raw, rid: str | None = None,
                    model_name: str | None = None) -> dict:
        """Score an already-decoded payload: the JSON path hands the
        parsed list in, the wire path (serve/wire/stream.py) hands the
        float32 matrix decoded STRAIGHT off its receive buffer — both
        then share every downstream step (validation, metrics, SLO
        taps, batching, the round(6) response discipline), which is
        what pins the two protocols bit-identical."""
        if self.multi is not None:
            return self._score_multi(raw, rid, model_name)
        model = self.store.current()
        rows = self._to_rows(raw, model.model.num_features)
        self._reject_nonfinite(rows, self.metrics, None)
        self.metrics.inc("requests_total")
        if self._slo is not None:
            # "requests" counts every scoring ATTEMPT (a shed raises out
            # of submit below and still counted here) — the denominator
            # of the windowed shed-rate signal
            self._slo.count("requests")
        t0 = time.monotonic()
        scores = self.batcher.submit(rows, rid=rid)
        if self._slo is not None:
            self._slo.observe("serve_p99_s", time.monotonic() - t0)
        # identity re-read AFTER scoring: a hot reload that swapped while
        # this request was queued means the dispatch scored through the
        # NEW model (the batcher fetches current() at dispatch time), and
        # stamping the pre-submit snapshot would attribute its scores to
        # the old digest.  A swap inside the dispatch-to-here window can
        # still mislabel, but the stamp now matches the scoring model in
        # every ordering the batcher can actually produce.
        model = self.store.current()
        return self._score_response(scores, model, rid)

    def _score_multi(self, raw, rid: str | None,
                     model_name: str | None) -> dict:
        """The ``/score/<model>`` path: resolve (admitting on demand
        under the cold-start guard), validate against THAT model's
        width, feed its micro-batcher, stamp its identity."""
        route = self._route
        if route is not None:
            parent, shadow, fraction = route
            resolved = model_name
            if resolved is None:
                sole = self.multi.sole()
                resolved = sole.name if sole is not None else None
            if (resolved == parent and rid is not None
                    and lifecycle_ctl.route_to_shadow(rid, fraction)):
                # deterministic rid-hash split: the same request routes
                # the same way on every worker and across restarts, so a
                # retry cannot flap between generations mid-ramp
                model_name = shadow
        tenant = self.multi.acquire(model_name)
        store = tenant.store
        if store is None:
            # evicted in the acquire→here window (a concurrent admission
            # under budget pressure chose this tenant as LRU victim);
            # re-acquire re-admits.  Typed None checks, not
            # except AttributeError — a genuine AttributeError from the
            # scorer must surface as the bug it is, never be misread as
            # an eviction and silently re-scored.
            tenant = self.multi.acquire(tenant.name)
            store = tenant.store
            if store is None:
                raise ModelColdStart(tenant.name)
        loaded = store.current()
        rows = self._to_rows(raw, loaded.model.num_features)
        self._reject_nonfinite(rows, tenant.metrics, tenant.name)
        tenant.metrics.inc("requests_total")
        if self._slo is not None:
            self._slo.count("requests")
            self._slo.count(f"requests:{tenant.name}")
        t0 = time.monotonic()
        scores = None
        for attempt in (0, 1):
            batcher = tenant.batcher
            try:
                if batcher is None:
                    raise BatcherClosed("tenant evicted mid-request")
                scores = batcher.submit(rows, rid=rid)
                break
            except BatcherClosed:
                # evicted between acquire and submit (budget pressure
                # from a concurrent admission): one re-acquire re-admits
                # — under a thrashing budget the request is slow, not
                # failed.  Losing the race TWICE degrades to a
                # retryable 503 (cold start), never a 500.
                if attempt:
                    raise ModelColdStart(tenant.name)
                tenant = self.multi.acquire(tenant.name)
        dt = time.monotonic() - t0
        if self._slo is not None:
            self._slo.observe("serve_p99_s", dt)
            self._slo.observe(f"serve_p99_s:{tenant.name}", dt)
        self._note_scores(tenant.name, scores)
        mirror = self._mirror
        if mirror is not None and tenant.name == mirror[0]:
            # bounded drop-on-full copy of parent traffic for the shadow
            # scorer; the serving path never blocks on the mirror
            self._mirror_q.append(rows)
        # identity re-stamp, same argument as the single-model path; an
        # eviction racing this re-read keeps the pre-submit stamp
        store = tenant.store
        if store is not None:
            try:
                loaded = store.current()
            except ModelNotLoaded:
                pass
        return self._score_response(scores, loaded, rid,
                                    model=tenant.name)

    def handle_lane(self, rows: np.ndarray, rid: str | None,
                    model_name: str | None) -> tuple[np.ndarray, str]:
        """Score a sibling worker's forwarded batch (lane-owner side).
        Deliberately NOT handle_rows: the sibling already did the
        request-level accounting (requests_total, SLO taps, NaN
        rejection, mirror/sketch feeds) when it admitted the rows —
        this path only needs the batch to coalesce into OUR tenant
        batcher alongside native traffic, which is what makes DRR and
        occupancy fleet-wide.  Returns the round(6) float64 scores and
        the resolved model name (same discipline as _score_response, so
        the sibling's replies stay bit-identical to local scoring)."""
        if self.multi is not None:
            tenant = self.multi.acquire(model_name)
            store = tenant.store
            if store is None:
                tenant = self.multi.acquire(tenant.name)
                store = tenant.store
                if store is None:
                    raise ModelColdStart(tenant.name)
            loaded = store.current()
            rows = self._to_rows(rows, loaded.model.num_features)
            scores = None
            for attempt in (0, 1):
                batcher = tenant.batcher
                try:
                    if batcher is None:
                        raise BatcherClosed("tenant evicted mid-request")
                    scores = batcher.submit(rows, rid=rid)
                    break
                except BatcherClosed:
                    if attempt:
                        raise ModelColdStart(tenant.name)
                    tenant = self.multi.acquire(tenant.name)
            name = tenant.name
        else:
            loaded = self.store.current()
            rows = self._to_rows(rows, loaded.model.num_features)
            scores = self.batcher.submit(rows, rid=rid)
            name = ""
        out = (scores[:, 0] if scores.ndim == 2 and scores.shape[1] == 1
               else scores)
        return np.asarray(out, np.float64).round(6), name

    def health(self) -> tuple[int, dict]:
        if self.multi is not None:
            # no disk rescan on the probe path: a balancer polling
            # /healthz every second must not pay O(models) stats —
            # discovery refreshes at /models and scoring requests
            models = self.multi.models(rescan=False)
            admitted = [n for n, i in models.items()
                        if i["state"] == "admitted"]
            out = {
                "ok": bool(admitted),
                "backend": self.config.backend,
                "models": models,
                "models_admitted": len(admitted),
                "budget_mb": self.config.model_budget_mb,
                "uptime_s": round(
                    time.time() - self.metrics.started_at, 1),
            }
            if self.worker_index is not None:
                out["worker_index"] = self.worker_index
            # a fleet with nothing admitted can still admit on demand,
            # but it serves no request RIGHT NOW — that is 503-degraded
            return (200 if admitted else 503), out
        try:
            m = self.store.current()
        except ModelNotLoaded:
            return 503, {"ok": False, "error": "no model loaded"}
        out = {
            "ok": True,
            "model_epoch": m.epoch,
            "model_digest": m.digest[:12],
            "model_verified": m.verified,
            "backend": self.config.backend,
            "queue_rows": self.batcher.queued_rows(),
            "uptime_s": round(time.time() - self.metrics.started_at, 1),
        }
        if self.worker_index is not None:
            out["worker_index"] = self.worker_index
        return 200, out

    def health_model(self, name: str) -> tuple[int, dict]:
        """``/healthz/<model>``: one tenant's detail.  404 unknown, 200
        admitted, 503 known-but-unroutable (cold / admitting / refused —
        the state says which)."""
        if self.multi is None:
            return 404, {"error": "single-model server; use /healthz"}
        models = self.multi.models(rescan=False)
        info = models.get(name)
        if info is None and self.multi.refresh_tenant(name):
            # one TARGETED disk check before 404ing (never a full
            # rescan — a balancer probing a dead name must not cost
            # O(models) stats): the probe may be for a bundle published
            # since the last discovery
            info = self.multi.models(rescan=False).get(name)
        if info is None:
            return 404, {"error": f"unknown model {name!r}"}
        out = {"ok": info["state"] == "admitted", "model": name, **info}
        return (200 if out["ok"] else 503), out

    def _wire_gauges(self) -> None:
        """Frame/lane gauges, set at render time on the process surface
        (the _unrouted series in multi-tenant mode): which role this
        worker plays in the shared lane and how the frame listener is
        doing.  A scrape landing on an arbitrary SO_REUSEPORT worker
        reads that worker's role — worker_index rides the same
        response."""
        reg = self.metrics.registry
        if self.frame_server is not None:
            reg.set_gauge("frame_connections",
                          self.frame_server.connections())
        if self.lane_server is not None:
            reg.set_gauge("lane_owner", 1)
            reg.set_gauge("lane_connections",
                          self.lane_server.connections())
        if self.lane is not None:
            st = self.lane.stats()
            reg.set_gauge("lane_owner", 0)
            reg.set_gauge("lane_connected", int(st["connected"]))
            reg.set_gauge("lane_forwarded_total", st["forwarded"])
            reg.set_gauge("lane_fallback_total", st["fallback"])

    def metrics_text(self) -> str:
        self._wire_gauges()
        if self.multi is not None:
            if self.worker_index is not None:
                self.multi.fleet.set_gauge("worker_index",
                                           self.worker_index)
            # fleet gauges + every admitted tenant's stpu_serve_* series
            # under its model label, + the unrouted surface (requests
            # that never resolved a tenant: 404s, malformed bodies) —
            # regrouped into one TYPE block per family inside
            from shifu_tensorflow_tpu.obs import device_obs_text

            text = self.multi.metrics_text(unrouted=self.metrics)
            if self._slo is not None:
                text += self._slo.render_prometheus()
            return text + device_obs_text()
        try:
            m = self.store.current()
            epoch, digest, verified = m.epoch, m.digest[:12], m.verified
        except ModelNotLoaded:
            epoch, digest, verified = -1, "", False
        if self.worker_index is not None:
            # /metrics is per-process by design; under --serve-workers
            # the kernel routes a scrape to an ARBITRARY worker, so each
            # response carries which one answered
            self.metrics.registry.set_gauge("worker_index",
                                            self.worker_index)
        text = self.metrics.render_prometheus(
            queue_rows=self.batcher.queued_rows(),
            model_epoch=epoch,
            model_digest=digest,
            model_verified=verified,
        )
        if self._slo is not None:
            # stpu_slo_* gauges ride every scrape: the supervisor policy
            # (ROADMAP item 4) reads the same signal the journal records
            text += self._slo.render_prometheus()
        # device/compiler leg + build identity, one shared renderer for
        # every scrape surface (obs.device_obs_text)
        from shifu_tensorflow_tpu.obs import device_obs_text

        return text + device_obs_text()


def _make_handler(server: ScoringServer):
    class Handler(BaseHTTPRequestHandler):
        # keep-alive: a load generator reusing connections must not pay a
        # TCP handshake per request
        protocol_version = "HTTP/1.1"
        server_version = "stpu-serve"
        # headers flush and the JSON body go out as separate segments;
        # with Nagle on, the second waits for the peer's delayed ACK —
        # measured ~100 ms p50 on LOOPBACK before this flag
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # route through structured logs
            log.debug("%s " + fmt, self.client_address[0], *args)

        #: correlation id of the request in flight on THIS handler
        #: thread (BaseHTTPRequestHandler is one-request-at-a-time per
        #: connection, one handler per connection thread)
        _rid: str | None = None

        def _reply(self, status: int, body: bytes,
                   content_type: str = "application/json",
                   extra_headers: dict | None = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            if self._rid is not None:
                # every response — 429 sheds and 500s included — echoes
                # the id, so a client log line and the fleet journal
                # meet at the same key
                self.send_header("X-Request-Id", self._rid)
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _reply_json(self, status: int, obj: dict,
                        extra_headers: dict | None = None) -> None:
            self._reply(status, json.dumps(obj).encode("utf-8"),
                        extra_headers=extra_headers)

        def do_GET(self):
            inbound = self.headers.get("X-Request-Id")
            self._rid = resolve_rid(inbound) if inbound else None
            if self.path == "/healthz":
                status, obj = server.health()
                self._reply_json(status, obj)
            elif self.path == "/metrics":
                self._reply(200, server.metrics_text().encode("utf-8"),
                            content_type="text/plain; version=0.0.4")
            elif self.path == "/models" and server.multi is not None:
                self._reply_json(200, {"models": server.multi.models()})
            else:
                m = _MODEL_PATH.fullmatch(self.path)
                if (m is not None and m.group(1) == "healthz"
                        and server.multi is not None):
                    status, obj = server.health_model(m.group(2))
                    self._reply_json(status, obj)
                    return
                self._reply_json(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            self._rid = resolve_rid(self.headers.get("X-Request-Id"))
            model_name: str | None = None
            if self.path != "/score":
                m = _MODEL_PATH.fullmatch(self.path)
                # named model routes exist only on a multi-tenant server
                # — a single-model server keeps its PR-3 path surface
                if (m is None or m.group(1) != "score"
                        or server.multi is None):
                    self._reply_json(
                        404, {"error": f"unknown path {self.path}"})
                    return
                model_name = m.group(2)
            try:
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    server.metrics.inc("errors_total")
                    self.close_connection = True
                    self._reply_json(
                        400, {"error": "unparseable Content-Length"})
                    return
                if length < 0:
                    # a negative length would slip past the limit check
                    # and turn rfile.read(-1) into read-until-EOF — which
                    # a keep-alive client never provides, leaking this
                    # handler thread forever
                    server.metrics.inc("errors_total")
                    self.close_connection = True
                    self._reply_json(
                        400, {"error": "negative Content-Length"})
                    return
                limit = server.max_body_bytes()
                if length > limit:
                    # refuse BEFORE reading: materializing a huge body
                    # (bytes → json → numpy) would blow memory long
                    # before the row-level admission checks could fire.
                    # The unread body poisons the keep-alive stream, so
                    # the connection closes with the refusal.
                    self.close_connection = True
                    server.metrics.inc("errors_total")
                    self._reply_json(413, {
                        "error": f"body of {length} bytes exceeds the "
                                 f"{limit}-byte limit; split the request"
                    })
                    return
                body = self.rfile.read(length)
                self._reply_json(200, server.handle_score(
                    body, self._rid, model_name))
            except _BadRequest as e:
                server.metrics.inc("errors_total")
                self._reply_json(400, {"error": str(e)})
            except UnknownModel as e:
                server.metrics.inc("errors_total")
                self._reply_json(
                    404, {"error": f"unknown model {e.args[0]!r}; "
                                   "GET /models lists the tenants"})
            except AmbiguousModel as e:
                server.metrics.inc("errors_total")
                self._reply_json(400, {"error": str(e)})
            except ModelColdStart as e:
                # admittable but still verifying/warming: same contract
                # as a shed — come back shortly, with a hint
                server.metrics.inc("errors_total")
                self._reply_json(
                    503,
                    {"error": str(e), "retry_after_s": e.retry_after_s},
                    extra_headers={"Retry-After": str(e.retry_after_s)},
                )
            except AdmissionRefused as e:
                server.metrics.inc("errors_total")
                self._reply_json(503, {"error": str(e)})
            except ShedLoad as e:
                # shed counter already bumped by the batcher; note_shed
                # feeds the SLO shed-rate window and journals the
                # CONDITION at most once per 5s (with this request's id)
                server.note_shed(self._rid, model_name)
                self._reply_json(
                    429,
                    {"error": "overloaded, retry later",
                     "retry_after_s": e.retry_after_s},
                    extra_headers={"Retry-After": str(e.retry_after_s)},
                )
            except RequestTooLarge as e:
                # ONLY the batcher's admission check maps to 413: a bare
                # ValueError out of the scorer is a server-side problem
                # (e.g. a mid-flight reload changed the feature width)
                # and falls through to the 500 handler below
                server.metrics.inc("errors_total")
                self._reply_json(413, {"error": str(e)})
            except (BatcherClosed, ModelNotLoaded) as e:
                server.metrics.inc("errors_total")
                self._reply_json(503, {"error": str(e)})
            except TimeoutError as e:
                server.metrics.inc("errors_total")
                self._reply_json(504, {"error": str(e)})
            except Exception as e:
                server.metrics.inc("errors_total")
                log.error("scoring request failed: %s: %s",
                          type(e).__name__, e)
                self._reply_json(
                    500, {"error": f"{type(e).__name__}: {e}"}
                )

    return Handler
