"""Micro-batching with shed-before-queue backpressure.

The throughput lever of online scoring is the same one the training side
pulls with scan-steps: per-dispatch cost (Python → jit call → XLA
program launch) is fixed, so N concurrent one-row requests dispatched
individually pay it N times, while one coalesced dispatch pays it once
(the difference the TF-system and tf.data papers call per-request vs
pipeline throughput).  ``MicroBatcher`` coalesces whatever requests are
queued into one dispatch of at most ``max_batch`` rows, waiting at most
``max_delay_s`` for peers to arrive, and pads the coalesced batch up to
the export/bucketing.py power-of-two ladder so the jitted scorer
compiles once per bucket, not once per batch length.

Backpressure is SHED-BEFORE-QUEUE: the admission queue is bounded at
``max_queue_rows`` and a request that would overflow it raises
:class:`ShedLoad` (the server maps it to 429 + Retry-After) instead of
being queued.  An unbounded queue never rejects anything — it just
converts overload into unbounded latency for everyone, which is strictly
worse than telling the slowest fraction of callers to come back later.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from shifu_tensorflow_tpu.export.bucketing import bucket_size, pad_rows
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve.batcher")


class ShedLoad(RuntimeError):
    """Admission refused: the queue is full.  Carries the Retry-After
    hint the HTTP layer forwards."""

    def __init__(self, retry_after_s: int, queued_rows: int):
        super().__init__(
            f"admission queue full ({queued_rows} rows queued); "
            f"retry after {retry_after_s}s"
        )
        self.retry_after_s = retry_after_s


class BatcherClosed(RuntimeError):
    """Submit after close(): the server is draining."""


class RequestTooLarge(ValueError):
    """A single request bigger than the admission bound — a client
    error (413), distinct from both shedding (the queue could NEVER
    hold it, retrying won't help) and from scorer-side ValueErrors
    (which are server bugs, not the client's payload)."""


class _Pending:
    __slots__ = ("rows", "event", "result", "error", "t_enqueue")

    def __init__(self, rows: np.ndarray):
        self.rows = rows
        self.event = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.t_enqueue = time.monotonic()


class MicroBatcher:
    """Coalesce concurrent ``submit`` calls into batched ``score_fn``
    dispatches on a single worker thread.

    ``score_fn(rows) -> scores`` receives a (n, f) float32 array whose n
    is always a ladder bucket size and must return an array whose axis 0
    matches; it runs on the batcher thread only, so a scorer that is
    merely single-thread-safe (EvalModel's documented contract) needs no
    extra locking here.
    """

    def __init__(
        self,
        score_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 256,
        max_delay_s: float = 0.005,
        max_queue_rows: int = 4096,
        retry_after_s: int = 1,
        metrics=None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._score = score_fn
        self.max_batch = max_batch
        self.max_delay_s = max(0.0, max_delay_s)
        self.max_queue_rows = max(max_batch, max_queue_rows)
        self.retry_after_s = retry_after_s
        self.metrics = metrics
        self._cond = threading.Condition()
        self._pending: deque[_Pending] = deque()
        self._queued_rows = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._thread.start()

    # ---- client side ----
    def queued_rows(self) -> int:
        with self._cond:
            return self._queued_rows

    def submit(self, rows: np.ndarray, timeout_s: float = 30.0) -> np.ndarray:
        """Score ``rows`` (n, f); blocks until the coalesced dispatch that
        includes them completes.  Raises :class:`ShedLoad` when admission
        would overflow the queue, :class:`BatcherClosed` when draining,
        TimeoutError if the dispatch does not complete in time, or the
        scorer's own exception."""
        n = rows.shape[0]
        if n < 1:
            raise ValueError("empty batch")
        if n > self.max_queue_rows:
            raise RequestTooLarge(
                f"request of {n} rows exceeds the admission bound "
                f"({self.max_queue_rows}); split it"
            )
        item = _Pending(rows)
        with self._cond:
            if self._closed:
                raise BatcherClosed("batcher is draining")
            # shed BEFORE queue: admitting past the bound converts
            # overload into latency collapse for every queued caller
            if self._queued_rows + n > self.max_queue_rows:
                if self.metrics is not None:
                    self.metrics.inc("shed_total")
                raise ShedLoad(self.retry_after_s, self._queued_rows)
            self._pending.append(item)
            self._queued_rows += n
            self._cond.notify_all()
        if not item.event.wait(timeout_s):
            # withdraw from the queue if the item was never taken: the
            # caller is gone, and leaving the rows behind would keep
            # consuming admission capacity AND device dispatches for
            # results nobody reads — amplifying exactly the overload the
            # timeout signals.  An already-taken item can't be recalled;
            # its result is simply dropped.
            with self._cond:
                if item in self._pending:
                    self._pending.remove(item)
                    self._queued_rows -= n
            raise TimeoutError(
                f"dispatch did not complete within {timeout_s}s"
            )
        if item.error is not None:
            raise item.error
        if self.metrics is not None:
            self.metrics.request_latency.record(
                time.monotonic() - item.t_enqueue
            )
        return item.result

    # ---- worker side ----
    def _take_batch(self) -> list[_Pending] | None:
        """Block until work (or close), honor the coalescing window, and
        pop up to max_batch rows' worth of requests — never splitting a
        request across dispatches (each caller gets exactly one batch's
        results)."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return None  # closed and drained
            # coalescing window: from the OLDEST queued request's arrival,
            # wait up to max_delay for peers — unless a full batch is
            # already here
            deadline = self._pending[0].t_enqueue + self.max_delay_s
            while self._queued_rows < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
                if not self._pending:  # spurious wake after drain
                    return self._take_batch()
            batch: list[_Pending] = []
            taken = 0
            while self._pending:
                nxt = self._pending[0]
                n = nxt.rows.shape[0]
                if batch and taken + n > self.max_batch:
                    break
                batch.append(self._pending.popleft())
                taken += n
            self._queued_rows -= taken
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            self._dispatch(batch)

    def _dispatch(self, batch: list[_Pending]) -> None:
        sizes = [p.rows.shape[0] for p in batch]
        n = sum(sizes)
        bucket = bucket_size(n)
        t0 = time.monotonic()
        try:
            # the concatenate is INSIDE the guard: coalesced requests can
            # disagree on row width (each was validated against whichever
            # model was current at its admission, and a hot reload can
            # change the width in between) — that must fail THESE callers,
            # not kill the worker thread and wedge every future submit
            x = (batch[0].rows if len(batch) == 1
                 else np.concatenate([p.rows for p in batch], axis=0))
            scores = np.asarray(self._score(pad_rows(x, bucket)))[:n]
        except BaseException as e:  # propagate to every waiting caller
            log.warning("dispatch of %d rows failed: %s: %s",
                        n, type(e).__name__, e)
            for p in batch:
                p.error = e
                p.event.set()
            return
        if self.metrics is not None:
            self.metrics.inc("batches_total")
            self.metrics.inc("rows_total", n)
            self.metrics.inc("padded_rows_total", bucket - n)
            self.metrics.batch_latency.record(time.monotonic() - t0)
        off = 0
        for p, sz in zip(batch, sizes):
            p.result = scores[off:off + sz]
            p.error = None
            off += sz
            p.event.set()

    def close(self, drain: bool = True) -> None:
        """Stop admitting; by default finish what is queued (each waiting
        caller gets its result), then stop the worker thread."""
        with self._cond:
            self._closed = True
            if not drain:
                for p in self._pending:
                    p.error = BatcherClosed("batcher closed before dispatch")
                    p.event.set()
                self._pending.clear()
                self._queued_rows = 0
            self._cond.notify_all()
        self._thread.join(timeout=30.0)
