"""Micro-batching with shed-before-queue backpressure and a pipelined
pack → dispatch → scatter dispatch path.

The throughput lever of online scoring is the same one the training side
pulls with scan-steps: per-dispatch cost (Python → jit call → XLA
program launch) is fixed, so N concurrent one-row requests dispatched
individually pay it N times, while one coalesced dispatch pays it once
(the difference the TF-system and tf.data papers call per-request vs
pipeline throughput).  ``MicroBatcher`` coalesces whatever requests are
queued into one dispatch of at most ``max_batch`` rows, waiting at most
``max_delay_s`` for peers to arrive, and pads the coalesced batch up to
the export/bucketing.py power-of-two ladder so the jitted scorer
compiles once per bucket, not once per batch length.

The dispatch path is a three-stage pipeline, the serving analogue of
tf.data's overlap-host-work-with-device-work discipline (arxiv
2101.12127): the device must never wait on host bookkeeping.

- **pack** (its own thread): pop a coalesced batch from the admission
  queue, concatenate and pad it to the bucket ladder;
- **dispatch** (its own thread): the only thread that touches
  ``score_fn`` — it does nothing but feed the device;
- **scatter** (its own thread): slice the batch's scores back into
  per-request results and wake the waiting callers.

The stages hand off through depth-bounded queues (double-buffering), so
while the device scores batch N the pack stage is already preparing
batch N+1 and the scatter stage is still distributing batch N-1 — under
load, per-request scatter and pad/pack cost disappears from the dispatch
critical path entirely.  The ``serve.pack`` / ``serve.dispatch`` /
``serve.scatter`` obs trace spans (obs/trace.py) measure each stage;
their totals summing past the batcher's wall clock is the overlap,
observable in any traced run.

Backpressure is SHED-BEFORE-QUEUE: the admission queue is bounded at
``max_queue_rows`` and a request that would overflow it raises
:class:`ShedLoad` (the server maps it to 429 + Retry-After) instead of
being queued.  An unbounded queue never rejects anything — it just
converts overload into unbounded latency for everyone, which is strictly
worse than telling the slowest fraction of callers to come back later.
The ``Retry-After`` hint is JITTERED uniformly over [0.5x, 1.5x] of the
configured value (the configured value is the mean): a shed wave echoed
back verbatim synchronizes every rejected client into one retry
thundering herd exactly one Retry-After later — on a server that just
proved it cannot absorb the first wave.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from shifu_tensorflow_tpu.export.bucketing import bucket_size, pad_rows
from shifu_tensorflow_tpu.obs import cost as obs_cost
from shifu_tensorflow_tpu.obs import datastats as obs_datastats
from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.obs import trace as obs_trace
from shifu_tensorflow_tpu.utils import faults, logs

log = logs.get("serve.batcher")


class ShedLoad(RuntimeError):
    """Admission refused: the queue is full.  Carries the (jittered)
    Retry-After hint the HTTP layer forwards, plus the configured mean
    it was drawn around."""

    def __init__(self, retry_after_s: int, queued_rows: int,
                 retry_after_mean_s: int | None = None):
        super().__init__(
            f"admission queue full ({queued_rows} rows queued); "
            f"retry after {retry_after_s}s"
        )
        self.retry_after_s = retry_after_s
        self.retry_after_mean_s = (
            retry_after_mean_s if retry_after_mean_s is not None
            else retry_after_s
        )


class BatcherClosed(RuntimeError):
    """Submit after close(): the server is draining."""


class RequestTooLarge(ValueError):
    """A single request bigger than the admission bound — a client
    error (413), distinct from both shedding (the queue could NEVER
    hold it, retrying won't help) and from scorer-side ValueErrors
    (which are server bugs, not the client's payload)."""


class _Pending:
    __slots__ = ("rows", "rid", "event", "result", "error", "t_enqueue")

    def __init__(self, rows: np.ndarray, rid: str | None = None):
        self.rows = rows
        self.rid = rid  # correlation id minted at serve ingress
        self.event = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.t_enqueue = time.monotonic()


class _Work:
    """One coalesced batch moving through the pipeline stages."""

    __slots__ = ("batch", "sizes", "n", "bucket", "rows", "padded",
                 "scores", "error", "dispatch_s", "queue_delay_s",
                 "via_lane")

    def __init__(self, batch: list[_Pending]):
        self.batch = batch
        self.sizes = [p.rows.shape[0] for p in batch]
        self.n = sum(self.sizes)
        self.bucket = bucket_size(self.n)
        # the coalesced PRE-padding matrix: what the shared dispatch
        # lane forwards (the owner re-coalesces and pads), and what the
        # fallback path pads locally
        self.rows: np.ndarray | None = None
        self.padded: np.ndarray | None = None
        # completed over the fleet lane: the device-truth accounting
        # (batches/padded-rows counters, serve_batch event, cost ledger)
        # happened at the lane owner's dispatch, not here
        self.via_lane = False
        self.scores: np.ndarray | None = None
        self.error: BaseException | None = None
        self.dispatch_s = 0.0
        # oldest member's admission → dispatch start: the time these
        # requests spent waiting on coalescing + the pipeline, split
        # from the device time in the journaled serve_batch event
        self.queue_delay_s = 0.0

    def rids(self) -> list[str]:
        return [p.rid for p in self.batch if p.rid]


class MicroBatcher:
    """Coalesce concurrent ``submit`` calls into batched ``score_fn``
    dispatches through the pack → dispatch → scatter pipeline.

    ``score_fn(rows) -> scores`` receives a (n, f) float32 array whose n
    is always a ladder bucket size and must return an array whose axis 0
    matches; it runs on the dispatch thread only, so a scorer that is
    merely single-thread-safe (EvalModel's documented contract) needs no
    extra locking here.

    Multi-tenant mode: pass ``scheduler`` (a
    :class:`~shifu_tensorflow_tpu.serve.tenancy.scheduler.DeviceScheduler`)
    and this batcher keeps its OWN pack and scatter threads but hands
    packed batches to the shared scheduler instead of a private dispatch
    thread — the device is one serialized resource, and weighted-fair
    arbitration between tenants has to happen where the dispatches
    queue, not per tenant.  ``model`` names the tenant: it rides the
    scheduler registration, the journaled ``serve_batch``/``shed``
    events, and the per-model metrics this batcher was handed.
    """

    def __init__(
        self,
        score_fn: Callable[[np.ndarray], np.ndarray],
        *,
        max_batch: int = 256,
        max_delay_s: float = 0.005,
        max_queue_rows: int = 4096,
        retry_after_s: int = 1,
        metrics=None,
        scheduler=None,
        model: str | None = None,
        weight: float = 1.0,
        lane=None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._score = score_fn
        self.model = model
        self.max_batch = max_batch
        self.max_delay_s = max(0.0, max_delay_s)
        self.max_queue_rows = max(max_batch, max_queue_rows)
        self.retry_after_s = retry_after_s
        self.metrics = metrics
        self._rng = random.Random()
        self._cond = threading.Condition()
        self._pending: deque[_Pending] = deque()
        self._queued_rows = 0
        # rows taken off the admission queue but not yet scattered —
        # the (up to three) coalesced batches inside the pipeline.
        # Admission sheds on _queued_rows alone (the documented bound);
        # the observability surfaces report queued + in-flight so the
        # gauge cannot read ~0 while a thousand rows are mid-pipeline.
        self._inflight_rows = 0
        self._closed = False
        # stage handoffs: depth 1 into dispatch (one packed batch staged
        # while one scores — deeper would just add queueing latency),
        # depth 2 out of it (the device may finish two batches before a
        # slow scatter catches up without ever stalling the dispatch)
        self._dispatch_q: queue.Queue[_Work | None] = queue.Queue(maxsize=1)
        self._scatter_q: queue.Queue[_Work | None] = queue.Queue(maxsize=2)
        # the batch the dispatch thread is INSIDE score_fn with right
        # now: score_fn callbacks (the server's ModelReleasedError retry)
        # read its rids for their journal events.  Written only by the
        # dispatching thread (the private dispatch thread, or the shared
        # scheduler's device thread); reference assignment, so readers
        # see a whole _Work or None.
        self._dispatching: _Work | None = None
        self._scheduler = scheduler
        self._sched_handle = None
        # fleet-shared dispatch lane (serve/wire/lane.py): packed
        # batches forward to the lane-owner worker when it is
        # reachable; anything else dispatches through the private path
        # below exactly as before
        self._lane = lane
        # chaos seam serve.dispatch (slow/error kinds): decided at
        # construction, like the trainer's per-step seam — a plan comes
        # from the environment at process start, and the steady-state
        # dispatch path must not pay the plan lookup's lock per batch
        self._fault_seam = faults.active() is not None
        tag = f"-{model}" if model else ""
        self._threads = [
            threading.Thread(target=self._pack_loop,
                             name=f"serve-pack{tag}", daemon=True),
            threading.Thread(target=self._scatter_loop,
                             name=f"serve-scatter{tag}", daemon=True),
        ]
        if scheduler is None:
            self._threads.append(
                threading.Thread(target=self._dispatch_loop,
                                 name="serve-dispatch", daemon=True))
        else:
            # register BEFORE the pack thread starts: the first packed
            # batch must find the tenant queue in place
            self._sched_handle = scheduler.register(
                model or "", self, weight=weight)
        for t in self._threads:
            t.start()

    # ---- client side ----
    def queued_rows(self) -> int:
        """Rows admitted but not yet scattered: the admission queue PLUS
        the batches moving through the pack/dispatch/scatter pipeline."""
        with self._cond:
            return self._queued_rows + self._inflight_rows

    def dispatching_rids(self) -> list[str]:
        """Correlation ids of the batch currently inside ``score_fn``
        (empty outside a dispatch) — the server's ModelReleasedError
        retry journals these so the event names the requests it
        re-scored."""
        work = self._dispatching
        return work.rids() if work is not None else []

    def _jittered_retry_after(self) -> int:
        """Uniform over [0.5x, 1.5x] of the configured value (which is
        therefore the mean), made integral by STOCHASTIC rounding — the
        HTTP Retry-After header is whole seconds, and deterministic
        round() would collapse the whole range to a constant at the
        default 1 s mean (every shed client told "1" retries in the same
        herd the jitter exists to break).  Floored at 1 s, which skews
        the effective mean slightly above a configured value of 1."""
        x = self.retry_after_s * self._rng.uniform(0.5, 1.5)
        n = int(x)
        if self._rng.random() < x - n:
            n += 1
        return max(1, n)

    def submit(self, rows: np.ndarray, timeout_s: float = 30.0,
               rid: str | None = None) -> np.ndarray:
        """Score ``rows`` (n, f); blocks until the coalesced dispatch that
        includes them completes.  ``rid`` is the request's correlation id
        (serve ingress mints it from/instead of ``X-Request-Id``) — it
        rides the request through pack → dispatch → scatter so the
        journaled ``serve_batch`` event lists every id its dispatch
        touched.  Raises :class:`ShedLoad` when admission would overflow
        the queue, :class:`BatcherClosed` when draining, TimeoutError if
        the dispatch does not complete in time, or the scorer's own
        exception."""
        n = rows.shape[0]
        if n < 1:
            raise ValueError("empty batch")
        if n > self.max_queue_rows:
            raise RequestTooLarge(
                f"request of {n} rows exceeds the admission bound "
                f"({self.max_queue_rows}); split it"
            )
        item = _Pending(rows, rid=rid)
        with self._cond:
            if self._closed:
                raise BatcherClosed("batcher is draining")
            # shed BEFORE queue: admitting past the bound converts
            # overload into latency collapse for every queued caller
            if self._queued_rows + n > self.max_queue_rows:
                if self.metrics is not None:
                    self.metrics.inc("shed_total")
                raise ShedLoad(self._jittered_retry_after(),
                               self._queued_rows,
                               retry_after_mean_s=self.retry_after_s)
            self._pending.append(item)
            self._queued_rows += n
            self._cond.notify_all()
        if not item.event.wait(timeout_s):
            # withdraw from the queue if the item was never taken: the
            # caller is gone, and leaving the rows behind would keep
            # consuming admission capacity AND device dispatches for
            # results nobody reads — amplifying exactly the overload the
            # timeout signals.  An already-taken item can't be recalled;
            # its result is simply dropped.
            with self._cond:
                if item in self._pending:
                    self._pending.remove(item)
                    self._queued_rows -= n
            raise TimeoutError(
                f"dispatch did not complete within {timeout_s}s"
            )
        if item.error is not None:
            raise item.error
        if self.metrics is not None:
            self.metrics.request_latency.record(
                time.monotonic() - item.t_enqueue
            )
        return item.result

    # ---- pack stage ----
    def _take_batch(self) -> list[_Pending] | None:
        """Block until work (or close), honor the coalescing window, and
        pop up to max_batch rows' worth of requests — never splitting a
        request across dispatches (each caller gets exactly one batch's
        results)."""
        with self._cond:
            while not self._pending and not self._closed:
                self._cond.wait()
            if not self._pending:
                return None  # closed and drained
            # coalescing window: from the OLDEST queued request's arrival,
            # wait up to max_delay for peers — unless a full batch is
            # already here
            deadline = self._pending[0].t_enqueue + self.max_delay_s
            while self._queued_rows < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
                if not self._pending:  # spurious wake after drain
                    return self._take_batch()
            batch: list[_Pending] = []
            taken = 0
            while self._pending:
                nxt = self._pending[0]
                n = nxt.rows.shape[0]
                if batch and taken + n > self.max_batch:
                    break
                batch.append(self._pending.popleft())
                taken += n
            self._queued_rows -= taken
            self._inflight_rows += taken
            return batch

    def _pack_loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                # cascade the drain sentinel.  Scheduler mode: wait for
                # the shared device thread to finish everything this
                # tenant submitted (each dispatched work lands in OUR
                # scatter queue before drain() observes it done), then
                # leave the scheduler so a later admission can re-use
                # the tenant name with a fresh batcher.  A drain that
                # TIMED OUT (wedged scorer) leaves batches staged:
                # their waiters get a typed BatcherClosed — retryable
                # at the routing layer — never a silent hang until
                # their own submit timeout.
                if self._lane is not None:
                    # lane forwards first: their replies land in OUR
                    # scatter queue and must beat the sentinel below
                    # (a timeout fails them over to private dispatch,
                    # which the scheduler drain then covers)
                    self._lane.drain(self)
                if self._scheduler is not None:
                    self._scheduler.drain(self._sched_handle)
                    dropped = self._scheduler.unregister(
                        self._sched_handle)
                    for work in dropped:
                        with self._cond:
                            self._inflight_rows -= work.n
                        err = BatcherClosed(
                            "tenant drained before dispatch")
                        for p in work.batch:
                            p.error = err
                            p.event.set()
                    self._scatter_q.put(None)
                else:
                    self._dispatch_q.put(None)
                return
            work = _Work(batch)
            with obs_trace.span("serve.pack"):
                try:
                    # single-source zero-copy fast path: when ONE
                    # pending request covers the whole dispatch its
                    # matrix passes through untouched (no concatenate),
                    # and pad_rows below no-ops when it already fills
                    # its bucket — so a frame-ingress matrix (a single
                    # memoryview off the wire, serve/wire/frame.py)
                    # reaches score_fn without ever being copied.
                    # The concatenate stays INSIDE the guard: coalesced
                    # requests can disagree on row width (each was
                    # validated against whichever model was current at
                    # its admission, and a hot reload can change the
                    # width in between) — that must fail THESE callers,
                    # not kill a pipeline thread and wedge every future
                    # submit
                    x = (batch[0].rows if len(batch) == 1
                         else np.concatenate([p.rows for p in batch],
                                             axis=0))
                    work.rows = x
                    if self._lane is None:
                        # data-observability tap (obs/datastats.py):
                        # feed the PRE-padding concat into this model's
                        # live windowed sketch — once per coalesced
                        # dispatch, on the pack thread (off the device
                        # path), before the ladder's zero rows could
                        # read as a distribution.  Lane mode defers the
                        # tap to whoever DISPATCHES (the owner's pack
                        # loop, or the fallback branch below) so no row
                        # is sketched twice fleet-wide.
                        mon = obs_datastats.active()
                        if mon is not None:
                            mon.observe(self.model or "default", x)
                        work.padded = pad_rows(x, work.bucket)
                    # lane mode pads NOWHERE here: the owner coalesces
                    # forwards from the whole fleet before padding once
                except BaseException as e:
                    work.error = e
            if (work.error is None and self._lane is not None
                    and self._lane.forward(self, work)):
                # the lane owns it now: its reply (or the dead-owner
                # failover) lands in our scatter queue by rid
                continue
            if self._lane is not None and work.error is None:
                # lane unreachable: private dispatch, the pre-lane path
                try:
                    mon = obs_datastats.active()
                    if mon is not None:
                        mon.observe(self.model or "default", work.rows)
                    work.padded = pad_rows(work.rows, work.bucket)
                except BaseException as e:
                    work.error = e
            if self._scheduler is not None:
                self._scheduler.submit(self._sched_handle, work)
            else:
                self._dispatch_q.put(work)

    def _lane_fallback(self, work: _Work) -> None:
        """Re-route a forwarded batch through the PRIVATE dispatch path
        (lane owner died or refused with a server-side status) — called
        by the LaneClient, possibly from its reader thread.  The work
        re-pads locally and re-enters exactly where a never-forwarded
        batch would have."""
        work.via_lane = False
        work.scores = None
        work.bucket = bucket_size(work.n)
        if work.error is None and work.padded is None:
            try:
                work.padded = pad_rows(work.rows, work.bucket)
            except BaseException as e:
                work.error = e
        if self._scheduler is not None:
            self._scheduler.submit(self._sched_handle, work)
        else:
            self._dispatch_q.put(work)

    # ---- dispatch stage ----
    def _dispatch_one(self, work: _Work) -> None:
        """Score one packed batch and hand it to the scatter stage — the
        dispatch-stage body, shared by the private dispatch thread
        (single-model mode) and the tenancy DeviceScheduler's device
        thread (which calls it under its weighted-fair arbitration).
        Must be entered by one thread at a time per scorer — both
        callers are single device threads by construction."""
        acct = obs_cost.active()
        t_env = time.monotonic()
        if work.error is None:
            t0 = t_env
            work.queue_delay_s = t0 - min(
                p.t_enqueue for p in work.batch)
            self._dispatching = work
            # payload bytes (pre-padding): the volume denominator of the
            # per-tenant cost ledger — captured before the pad copy is
            # dropped below
            nbytes = (work.padded.itemsize * work.n * work.padded.shape[1]
                      if work.padded is not None and work.padded.ndim == 2
                      else 0)
            with obs_trace.span("serve.dispatch"):
                try:
                    if self._fault_seam:
                        # slow/error kinds land INSIDE the dispatch
                        # timing so an injected lag shows up exactly
                        # where a slow device would
                        faults.check("serve.dispatch")
                    work.scores = np.asarray(self._score(work.padded))
                except BaseException as e:
                    work.error = e
                finally:
                    self._dispatching = None
            work.dispatch_s = time.monotonic() - t0
            work.padded = None  # the pad copy is dead weight now
            if acct is not None:
                # cost leg (obs/cost.py): device-seconds + the DRR
                # currency (padded-row-seconds) attributed to this
                # tenant — the scheduler charged bucket rows, so the
                # ledger does too
                acct.note_dispatch(self.model, dispatch_s=work.dispatch_s,
                                   rows=work.n, bucket_rows=work.bucket,
                                   nbytes=nbytes)
        self._scatter_q.put(work)
        if acct is not None:
            # the device lane's busy envelope (scoring + handoff):
            # per-tenant device-seconds must conserve against this
            acct.note_busy(time.monotonic() - t_env)

    def _dispatch_loop(self) -> None:
        while True:
            work = self._dispatch_q.get()
            if work is None:
                self._scatter_q.put(None)
                return
            self._dispatch_one(work)

    # ---- scatter stage ----
    def _scatter_loop(self) -> None:
        while True:
            work = self._scatter_q.get()
            if work is None:
                return
            with obs_trace.span("serve.scatter"):
                self._scatter(work)

    def _scatter(self, work: _Work) -> None:
        with self._cond:
            self._inflight_rows -= work.n
        if work.error is not None:
            # propagate to every waiting caller of THIS batch
            log.warning("dispatch of %d rows failed: %s: %s",
                        work.n, type(work.error).__name__, work.error)
            for p in work.batch:
                p.error = work.error
                p.event.set()
            return
        if self.metrics is not None and not work.via_lane:
            # lane-completed batches: the device dispatch (and its
            # batches/rows/padded accounting + serve_batch event +
            # cost ledger) happened at the lane OWNER — counting it
            # here too would double every fleet-wide aggregate
            self.metrics.inc("batches_total")
            self.metrics.inc("rows_total", work.n)
            self.metrics.inc("padded_rows_total", work.bucket - work.n)
            self.metrics.batch_latency.record(work.dispatch_s)
        if obs_journal.active() is not None and not work.via_lane:
            # one event per coalesced DISPATCH (never per request — the
            # event rate is bounded by 1/max_delay, not the request
            # rate), carrying the correlation ids it scored: the causal
            # record `obs trace <rid>` reconstructs a request's
            # admission-wait vs device-time split from
            rids = work.rids()
            if rids:
                extra = {"model": self.model} if self.model else {}
                obs_journal.emit(
                    "serve_batch", plane="serve", rids=rids,
                    requests=len(work.batch), rows=work.n,
                    bucket=work.bucket,
                    queue_delay_s=round(work.queue_delay_s, 6),
                    dispatch_s=round(work.dispatch_s, 6),
                    **extra,
                )
        scores = work.scores[:work.n]
        off = 0
        for p, sz in zip(work.batch, work.sizes):
            p.result = scores[off:off + sz]
            p.error = None
            off += sz
            p.event.set()

    def close(self, drain: bool = True) -> None:
        """Stop admitting; by default finish what is queued (each waiting
        caller gets its result), then stop the pipeline threads — the
        drain sentinel cascades pack → dispatch → scatter so every
        in-flight batch scatters before the threads exit."""
        with self._cond:
            self._closed = True
            if not drain:
                for p in self._pending:
                    p.error = BatcherClosed("batcher closed before dispatch")
                    p.event.set()
                self._pending.clear()
                self._queued_rows = 0
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=30.0)
