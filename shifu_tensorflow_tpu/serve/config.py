"""Serving configuration — the ``shifu.tpu.serve-*`` surface as a typed
dataclass, resolved with the framework's usual precedence (built-in
defaults → ``--globalconfig`` XML/JSON layers → CLI flags).

Kept import-light on purpose: the CLI parses ``--help`` and resolves
config without paying the jax import the server itself needs — the same
discipline as train/__main__.py.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from shifu_tensorflow_tpu.config import keys as K


@dataclass(frozen=True)
class ServeConfig:
    """Everything the scoring server needs to run — the WorkerConfig
    analogue for the serving plane (JSON-bridgeable via to/from_json so a
    supervisor can ship it to a subprocess the same way).

    Exactly one of ``model_dir`` (single-model server, the PR-3 path,
    byte-for-byte unchanged) and ``models_dir`` (multi-tenant: every
    immediate subdirectory is a tenant routed at ``/score/<model>``)
    must be set."""

    model_dir: str | None = None
    host: str = K.DEFAULT_SERVE_HOST
    port: int = K.DEFAULT_SERVE_PORT
    backend: str = K.DEFAULT_SERVE_BACKEND
    max_batch: int = K.DEFAULT_SERVE_MAX_BATCH
    max_delay_ms: float = K.DEFAULT_SERVE_MAX_DELAY_MS
    max_queue_rows: int = K.DEFAULT_SERVE_QUEUE_ROWS
    retry_after_s: int = K.DEFAULT_SERVE_RETRY_AFTER_S
    reload_poll_ms: int = K.DEFAULT_SERVE_RELOAD_POLL_MS
    workers: int = K.DEFAULT_SERVE_WORKERS
    # SLO-driven autoscaling (serve/autoscale.py, run by the supervisor):
    # workers_max > workers turns the policy loop on — workers is then
    # the FLOOR, workers_max the ceiling.  0 (default) = off.
    workers_max: int = K.DEFAULT_SERVE_WORKERS_MAX
    autoscale_cooldown_s: float = K.DEFAULT_SERVE_AUTOSCALE_COOLDOWN_S
    autoscale_ticks: int = K.DEFAULT_SERVE_AUTOSCALE_TICKS
    autoscale_recovery_ticks: int = (
        K.DEFAULT_SERVE_AUTOSCALE_RECOVERY_TICKS)
    autoscale_poll_s: float = K.DEFAULT_SERVE_AUTOSCALE_POLL_S
    # supervisor /metrics listener port (stpu_serve_scale_* gauges +
    # restart-budget burn); 0 = off
    supervisor_port: int = K.DEFAULT_SERVE_SUPERVISOR_PORT
    # zero-copy columnar wire protocol (serve/wire/): 0 = frame listener
    # off, -1 = ephemeral port (tests), > 0 = fixed (SO_REUSEPORT when
    # workers > 1, like the HTTP port)
    frame_port: int = K.DEFAULT_SERVE_FRAME_PORT
    frame_max_rows: int = K.DEFAULT_SERVE_FRAME_MAX_ROWS
    # fleet-wide shared dispatch lane: the lowest-index worker owns
    # device dispatch, siblings forward packed batches over a UDS
    shared_lane: bool = K.DEFAULT_SERVE_SHARED_LANE
    # multi-tenant (serve/tenancy/) — shifu.tpu.serve-model-* keys
    models_dir: str | None = None
    model_budget_mb: float = K.DEFAULT_SERVE_MODEL_BUDGET_MB
    model_admit_wait_s: float = K.DEFAULT_SERVE_MODEL_ADMIT_WAIT_S
    # ((model, weight), ...) — a tuple of pairs, not a dict, so the
    # frozen dataclass stays hashable and asdict/from_json round-trips
    tenant_weights: tuple = ()

    def __post_init__(self):
        if bool(self.model_dir) == bool(self.models_dir):
            raise ValueError(
                "exactly one of --model-dir (single model) and "
                f"--models-dir ({K.SERVE_MODELS_DIR}, multi-tenant) "
                "must be set"
            )
        if self.model_budget_mb < 0:
            raise ValueError(f"{K.SERVE_MODEL_BUDGET_MB} must be >= 0")
        if self.model_admit_wait_s <= 0:
            raise ValueError(f"{K.SERVE_MODEL_ADMIT_WAIT_S} must be > 0")
        for name, w in self.tenant_weights:
            if float(w) <= 0:
                raise ValueError(
                    f"{K.SERVE_TENANT_WEIGHT_PREFIX}{name} must be > 0"
                )
        if self.workers < 1:
            raise ValueError(f"{K.SERVE_WORKERS} must be >= 1")
        if self.workers_max and self.workers_max < self.workers:
            raise ValueError(
                f"{K.SERVE_WORKERS_MAX} ({self.workers_max}) must be 0 "
                f"(autoscale off) or >= {K.SERVE_WORKERS} "
                f"({self.workers}): serve-workers is the autoscaler's "
                "floor"
            )
        if self.autoscale_cooldown_s < 0:
            raise ValueError(f"{K.SERVE_AUTOSCALE_COOLDOWN_S} must be >= 0")
        if self.autoscale_ticks < 1 or self.autoscale_recovery_ticks < 1:
            raise ValueError(
                f"{K.SERVE_AUTOSCALE_TICKS} and "
                f"{K.SERVE_AUTOSCALE_RECOVERY_TICKS} must be >= 1"
            )
        if self.autoscale_poll_s <= 0:
            raise ValueError(f"{K.SERVE_AUTOSCALE_POLL_S} must be > 0")
        if self.backend not in ("native", "cpp", "saved_model"):
            raise ValueError(
                f"unknown {K.SERVE_BACKEND} value {self.backend!r} "
                "(native | cpp | saved_model)"
            )
        if self.max_batch < 1:
            raise ValueError(f"{K.SERVE_MAX_BATCH} must be >= 1")
        if self.max_queue_rows < self.max_batch:
            raise ValueError(
                f"{K.SERVE_QUEUE_ROWS} ({self.max_queue_rows}) must be >= "
                f"{K.SERVE_MAX_BATCH} ({self.max_batch}): a queue smaller "
                "than one dispatch could never fill a batch"
            )
        if self.frame_port < -1:
            raise ValueError(
                f"{K.SERVE_FRAME_PORT} must be 0 (off), -1 (ephemeral) "
                f"or a port number, got {self.frame_port}"
            )
        if self.frame_max_rows == 0:
            # 0 = track the admission bound, whatever max_queue_rows
            # resolved to (frozen dataclass: assign around the freeze)
            object.__setattr__(self, "frame_max_rows", self.max_queue_rows)
        if self.frame_max_rows < 1:
            raise ValueError(f"{K.SERVE_FRAME_MAX_ROWS} must be >= 1")
        if self.frame_max_rows > self.max_queue_rows:
            raise ValueError(
                f"{K.SERVE_FRAME_MAX_ROWS} ({self.frame_max_rows}) must "
                f"be <= {K.SERVE_QUEUE_ROWS} ({self.max_queue_rows}): a "
                "frame the admission bound can never admit would always "
                "be refused after the bytes were already shipped"
            )

    def weight_for(self, model: str) -> float:
        """The tenant's DRR weight (default 1.0)."""
        for name, w in self.tenant_weights:
            if name == model:
                return float(w)
        return K.DEFAULT_SERVE_TENANT_WEIGHT

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "ServeConfig":
        d = dict(d)
        # JSON turns the weight pairs into lists; restore hashable form
        d["tenant_weights"] = tuple(
            (str(n), float(w)) for n, w in d.get("tenant_weights", ())
        )
        return cls(**d)


def _tenant_weights(args, conf) -> tuple:
    """Merge ``shifu.tpu.serve-tenant-weight-<model>`` conf keys with
    repeated ``--tenant-weight model=W`` flags (CLI wins per model)."""
    weights: dict[str, float] = {}
    for key, value in conf.items():
        if key.startswith(K.SERVE_TENANT_WEIGHT_PREFIX):
            model = key[len(K.SERVE_TENANT_WEIGHT_PREFIX):]
            if model:
                weights[model] = float(value)
    for spec in getattr(args, "tenant_weight", None) or ():
        model, sep, w = spec.partition("=")
        if not sep or not model:
            raise ValueError(
                f"--tenant-weight expects model=WEIGHT, got {spec!r}"
            )
        weights[model] = float(w)
    return tuple(sorted(weights.items()))


def resolve_serve_config(args, conf) -> ServeConfig:
    """CLI flag wins, then the conf key, then the built-in default — the
    same resolution contract trainer_extras/worker_runtime_kwargs use, so
    a globalconfig file can drive every serve knob without flags."""

    def pick(flag, key, default, get):
        v = getattr(args, flag, None)
        return v if v is not None else get(key, default)

    model_dir = getattr(args, "model_dir", None)
    models_dir = getattr(args, "models_dir", None)
    if model_dir is None and models_dir is None:
        # the conf key chooses the serving mode only when NO CLI flag
        # named a model source: an explicit --model-dir must not be
        # vetoed by a fleet-wide XML that sets serve-models-dir (CLI
        # wins, per the resolver's contract)
        models_dir = conf.get(K.SERVE_MODELS_DIR,
                              K.DEFAULT_SERVE_MODELS_DIR)
    return ServeConfig(
        model_dir=model_dir,
        models_dir=models_dir or None,
        model_budget_mb=pick("model_budget_mb", K.SERVE_MODEL_BUDGET_MB,
                             K.DEFAULT_SERVE_MODEL_BUDGET_MB,
                             conf.get_float),
        model_admit_wait_s=pick("model_admit_wait",
                                K.SERVE_MODEL_ADMIT_WAIT_S,
                                K.DEFAULT_SERVE_MODEL_ADMIT_WAIT_S,
                                conf.get_float),
        tenant_weights=_tenant_weights(args, conf),
        host=pick("host", K.SERVE_HOST, K.DEFAULT_SERVE_HOST, conf.get),
        port=pick("port", K.SERVE_PORT, K.DEFAULT_SERVE_PORT, conf.get_int),
        backend=pick("backend", K.SERVE_BACKEND, K.DEFAULT_SERVE_BACKEND,
                     conf.get),
        max_batch=pick("max_batch", K.SERVE_MAX_BATCH,
                       K.DEFAULT_SERVE_MAX_BATCH, conf.get_int),
        max_delay_ms=pick("max_delay_ms", K.SERVE_MAX_DELAY_MS,
                          K.DEFAULT_SERVE_MAX_DELAY_MS, conf.get_float),
        max_queue_rows=pick("queue_rows", K.SERVE_QUEUE_ROWS,
                            K.DEFAULT_SERVE_QUEUE_ROWS, conf.get_int),
        retry_after_s=pick("retry_after", K.SERVE_RETRY_AFTER_S,
                           K.DEFAULT_SERVE_RETRY_AFTER_S, conf.get_int),
        reload_poll_ms=pick("reload_poll_ms", K.SERVE_RELOAD_POLL_MS,
                            K.DEFAULT_SERVE_RELOAD_POLL_MS, conf.get_int),
        workers=pick("serve_workers", K.SERVE_WORKERS,
                     K.DEFAULT_SERVE_WORKERS, conf.get_int),
        workers_max=pick("serve_workers_max", K.SERVE_WORKERS_MAX,
                         K.DEFAULT_SERVE_WORKERS_MAX, conf.get_int),
        autoscale_cooldown_s=pick(
            "autoscale_cooldown", K.SERVE_AUTOSCALE_COOLDOWN_S,
            K.DEFAULT_SERVE_AUTOSCALE_COOLDOWN_S, conf.get_float),
        autoscale_ticks=pick(
            "autoscale_ticks", K.SERVE_AUTOSCALE_TICKS,
            K.DEFAULT_SERVE_AUTOSCALE_TICKS, conf.get_int),
        autoscale_recovery_ticks=pick(
            "autoscale_recovery_ticks", K.SERVE_AUTOSCALE_RECOVERY_TICKS,
            K.DEFAULT_SERVE_AUTOSCALE_RECOVERY_TICKS, conf.get_int),
        autoscale_poll_s=pick(
            "autoscale_poll", K.SERVE_AUTOSCALE_POLL_S,
            K.DEFAULT_SERVE_AUTOSCALE_POLL_S, conf.get_float),
        supervisor_port=pick(
            "supervisor_port", K.SERVE_SUPERVISOR_PORT,
            K.DEFAULT_SERVE_SUPERVISOR_PORT, conf.get_int),
        frame_port=pick("frame_port", K.SERVE_FRAME_PORT,
                        K.DEFAULT_SERVE_FRAME_PORT, conf.get_int),
        frame_max_rows=pick("frame_max_rows", K.SERVE_FRAME_MAX_ROWS,
                            K.DEFAULT_SERVE_FRAME_MAX_ROWS, conf.get_int),
        shared_lane=pick("shared_lane", K.SERVE_SHARED_LANE,
                         K.DEFAULT_SERVE_SHARED_LANE, conf.get_bool),
    )
