"""Serving configuration — the ``shifu.tpu.serve-*`` surface as a typed
dataclass, resolved with the framework's usual precedence (built-in
defaults → ``--globalconfig`` XML/JSON layers → CLI flags).

Kept import-light on purpose: the CLI parses ``--help`` and resolves
config without paying the jax import the server itself needs — the same
discipline as train/__main__.py.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass

from shifu_tensorflow_tpu.config import keys as K


@dataclass(frozen=True)
class ServeConfig:
    """Everything the scoring server needs to run — the WorkerConfig
    analogue for the serving plane (JSON-bridgeable via to/from_json so a
    supervisor can ship it to a subprocess the same way)."""

    model_dir: str
    host: str = K.DEFAULT_SERVE_HOST
    port: int = K.DEFAULT_SERVE_PORT
    backend: str = K.DEFAULT_SERVE_BACKEND
    max_batch: int = K.DEFAULT_SERVE_MAX_BATCH
    max_delay_ms: float = K.DEFAULT_SERVE_MAX_DELAY_MS
    max_queue_rows: int = K.DEFAULT_SERVE_QUEUE_ROWS
    retry_after_s: int = K.DEFAULT_SERVE_RETRY_AFTER_S
    reload_poll_ms: int = K.DEFAULT_SERVE_RELOAD_POLL_MS
    workers: int = K.DEFAULT_SERVE_WORKERS

    def __post_init__(self):
        if self.workers < 1:
            raise ValueError(f"{K.SERVE_WORKERS} must be >= 1")
        if self.backend not in ("native", "cpp", "saved_model"):
            raise ValueError(
                f"unknown {K.SERVE_BACKEND} value {self.backend!r} "
                "(native | cpp | saved_model)"
            )
        if self.max_batch < 1:
            raise ValueError(f"{K.SERVE_MAX_BATCH} must be >= 1")
        if self.max_queue_rows < self.max_batch:
            raise ValueError(
                f"{K.SERVE_QUEUE_ROWS} ({self.max_queue_rows}) must be >= "
                f"{K.SERVE_MAX_BATCH} ({self.max_batch}): a queue smaller "
                "than one dispatch could never fill a batch"
            )

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "ServeConfig":
        return cls(**d)


def resolve_serve_config(args, conf) -> ServeConfig:
    """CLI flag wins, then the conf key, then the built-in default — the
    same resolution contract trainer_extras/worker_runtime_kwargs use, so
    a globalconfig file can drive every serve knob without flags."""

    def pick(flag, key, default, get):
        v = getattr(args, flag, None)
        return v if v is not None else get(key, default)

    return ServeConfig(
        model_dir=args.model_dir,
        host=pick("host", K.SERVE_HOST, K.DEFAULT_SERVE_HOST, conf.get),
        port=pick("port", K.SERVE_PORT, K.DEFAULT_SERVE_PORT, conf.get_int),
        backend=pick("backend", K.SERVE_BACKEND, K.DEFAULT_SERVE_BACKEND,
                     conf.get),
        max_batch=pick("max_batch", K.SERVE_MAX_BATCH,
                       K.DEFAULT_SERVE_MAX_BATCH, conf.get_int),
        max_delay_ms=pick("max_delay_ms", K.SERVE_MAX_DELAY_MS,
                          K.DEFAULT_SERVE_MAX_DELAY_MS, conf.get_float),
        max_queue_rows=pick("queue_rows", K.SERVE_QUEUE_ROWS,
                            K.DEFAULT_SERVE_QUEUE_ROWS, conf.get_int),
        retry_after_s=pick("retry_after", K.SERVE_RETRY_AFTER_S,
                           K.DEFAULT_SERVE_RETRY_AFTER_S, conf.get_int),
        reload_poll_ms=pick("reload_poll_ms", K.SERVE_RELOAD_POLL_MS,
                            K.DEFAULT_SERVE_RELOAD_POLL_MS, conf.get_int),
        workers=pick("serve_workers", K.SERVE_WORKERS,
                     K.DEFAULT_SERVE_WORKERS, conf.get_int),
    )
