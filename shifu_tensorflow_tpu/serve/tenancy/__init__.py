"""Multi-tenant serving: one fleet, many models, weighted-fair device
sharing.

The serve plane through PR 5 ran one model per process; production
traffic means hundreds of ModelConfigs behind one endpoint (ROADMAP
item 3; the reference's eval module is exactly a multi-model batch
scorer — any exported bundle behind the ``Computable`` interface).  This
package layers tenancy on the existing planes without re-implementing
any of them:

- :mod:`~shifu_tensorflow_tpu.serve.tenancy.scheduler` — one shared
  device dispatch thread arbitrating per-tenant micro-batcher queues
  with weighted deficit round-robin, so a hot tenant cannot starve the
  rest;
- :mod:`~shifu_tensorflow_tpu.serve.tenancy.store` — the MultiModelStore:
  named tenants admitted under a memory budget with LRU eviction, each
  admission running the full PR-3 verify-before-admit chain and the
  PR-5 warm ladder BEFORE the model becomes routable, each eviction
  releasing through the compute-lock discipline.

``serve/server.py`` routes ``/score/<model>`` onto this package when
``shifu.tpu.serve-models-dir`` is set; the single-model path is
untouched.
"""

from shifu_tensorflow_tpu.serve.tenancy.scheduler import DeviceScheduler
from shifu_tensorflow_tpu.serve.tenancy.store import (
    AdmissionRefused,
    ModelColdStart,
    MultiModelStore,
    UnknownModel,
)

__all__ = [
    "DeviceScheduler",
    "MultiModelStore",
    "UnknownModel",
    "ModelColdStart",
    "AdmissionRefused",
]
