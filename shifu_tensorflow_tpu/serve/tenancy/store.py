"""MultiModelStore: named tenants admitted under a memory budget with
LRU eviction, verify-before-admit, and warm-before-routable.

One serving process, many exported bundles: every immediate
subdirectory of ``shifu.tpu.serve-models-dir`` holding an export bundle
is a *tenant*, named by the subdirectory and routed at
``/score/<model>``.  Each tenant composes the SAME machinery the
single-model server uses — a :class:`~shifu_tensorflow_tpu.serve.
model_store.ModelStore` (manifest verification, hot reload, warm
ladder) and a :class:`~shifu_tensorflow_tpu.serve.batcher.MicroBatcher`
(coalescing, shed-before-queue) — so an admitted tenant scores
bit-identically to a single-model server on the same bundle.  What this
layer adds is the tenancy policy:

- **Admission** runs the full PR-3 verify chain and the PR-5 warm
  ladder *before* the model becomes routable (both happen inside the
  ModelStore constructor; a corrupt or unwarmable bundle is refused and
  every other tenant keeps serving).  Admission is single-flight per
  tenant: concurrent cold-start requests share one admission, waiting
  at most ``shifu.tpu.serve-model-admit-wait`` seconds (the cold-start
  guard) before shedding 503 + Retry-After — the admission itself
  always runs to completion in the background, so a timed-out caller's
  retry lands on a warm model.
- **Budget + LRU eviction**: admitted bundle bytes (manifest-covered
  file sizes — the proxy for resident weights + compiled ladder) are
  capped at ``shifu.tpu.serve-model-budget-mb``.  Admitting past the
  cap evicts least-recently-*used* tenants first; eviction drains the
  tenant's batcher (in-flight dispatches finish) and releases the model
  through EvalModel's compute lock — the PR-3 discipline, so no
  dispatch is ever torn down mid-score.  An evicted tenant stays known
  and re-admits on demand.
- **Weighted fair dispatch**: every tenant batcher feeds the one shared
  :class:`~shifu_tensorflow_tpu.serve.tenancy.scheduler.DeviceScheduler`
  under its ``shifu.tpu.serve-tenant-weight-<model>`` weight.
- **Per-model observability**: each tenant carries its own ServeMetrics
  registry (rendered with a ``model="<name>"`` label), its ModelStore
  journals ``reload``/``reload_refused`` with the model dimension, the
  store journals ``model_admit``/``model_evict``/``model_admit_failed``
  lifecycle events, and every admission registers the tenant's SLO
  signals on the active watchdog.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from shifu_tensorflow_tpu.export.bucketing import ladder
from shifu_tensorflow_tpu.export.saved_model import (
    NATIVE_ARCH,
    NATIVE_MANIFEST,
    NATIVE_WEIGHTS,
)
from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.obs import memory as obs_memory
from shifu_tensorflow_tpu.obs import slo as obs_slo
from shifu_tensorflow_tpu.obs.registry import MetricsRegistry
from shifu_tensorflow_tpu.serve.batcher import MicroBatcher
from shifu_tensorflow_tpu.serve.metrics import ServeMetrics
from shifu_tensorflow_tpu.serve.model_store import ModelStore, _aot_fields
from shifu_tensorflow_tpu.serve.tenancy.scheduler import DeviceScheduler
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve.tenancy")

#: tenant names are export subdirectory names routed in URL paths —
#: same charset as request ids, no separators, no dotfiles/traversal
_NAME_OK = re.compile(r"^(?!\.)[0-9A-Za-z._-]{1,64}$")

#: a refused tenant re-attempts admission on demand, but not more often
#: than this — re-verifying a corrupt bundle reads every covered file,
#: and a request flood must not turn that into a disk flood
_REFUSAL_HOLDDOWN_S = 5.0

#: fleet-level counters, pre-registered so the scrape surface is stable
_FLEET_COUNTERS = (
    "admissions_total",         # tenants admitted (initial + re-admits)
    "evictions_total",          # tenants evicted (budget pressure)
    "admit_failures_total",     # admissions refused (corrupt/budget)
    "cold_start_timeouts_total",  # requests that outwaited the guard
    "unknown_model_total",      # /score/<name> for no known tenant
)


def _merge_exposition(parts: list[str]) -> str:
    """Regroup several Prometheus text renders into one valid
    exposition: one ``# TYPE`` line per metric family, all its samples
    contiguous beneath it, family order = first appearance.  The
    renderer always emits a family's TYPE line before its samples
    (histogram ``_bucket``/``_count``/``_sum`` lines belong to the
    family whose TYPE preceded them), so attribution is positional."""
    type_lines: dict[str, str] = {}
    samples: dict[str, list[str]] = {}
    order: list[str] = []
    for text in parts:
        family = None
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("# TYPE "):
                family = line.split()[2]
                if family not in type_lines:
                    type_lines[family] = line
                    samples[family] = []
                    order.append(family)
            elif family is not None:
                samples[family].append(line)
    out: list[str] = []
    for family in order:
        out.append(type_lines[family])
        out.extend(samples[family])
    return "\n".join(out) + "\n" if out else ""


class UnknownModel(LookupError):
    """No tenant of that name exists under the models dir → 404."""


class AmbiguousModel(RuntimeError):
    """Legacy ``/score`` (no model segment) against a store with more
    than one tenant — the client must name one → 400."""


class ModelColdStart(RuntimeError):
    """The model is admittable but its admission (verify + warm) is
    still running and the caller outwaited the cold-start guard → 503 +
    Retry-After."""

    def __init__(self, model: str, retry_after_s: int = 2):
        super().__init__(
            f"model {model!r} is warming up; retry in {retry_after_s}s"
        )
        self.model = model
        self.retry_after_s = retry_after_s


class AdmissionRefused(RuntimeError):
    """The bundle cannot be admitted: corrupt/unwarmable artifact, or it
    can never fit the memory budget → 503."""


class _Tenant:
    """One named model's tenancy record.  ``state`` transitions under
    the store lock: cold → admitting → admitted → cold (evicted) or
    refused (bad artifact), and back through admitting on demand."""

    __slots__ = ("name", "dir", "state", "store", "batcher", "metrics",
                 "cost_bytes", "last_used", "admitted_at", "admit_event",
                 "error", "refused_at")

    def __init__(self, name: str, bundle_dir: str):
        self.name = name
        self.dir = bundle_dir
        self.state = "cold"
        self.store: ModelStore | None = None
        self.batcher: MicroBatcher | None = None
        self.metrics: ServeMetrics | None = None
        self.cost_bytes = 0
        self.last_used = 0.0
        self.admitted_at = 0.0
        self.admit_event: threading.Event | None = None
        self.error: str | None = None
        self.refused_at = 0.0


class MultiModelStore:
    def __init__(self, config, *, warm: bool = True, lane=None):
        self.config = config
        # fleet-shared dispatch lane (serve/wire/lane.py): non-None only
        # on sibling workers — every admitted tenant's batcher forwards
        # its packed batches down it instead of feeding the local
        # scheduler (which stays registered as the fallback path)
        self.lane = lane
        self.root = config.models_dir
        if not os.path.isdir(self.root):
            raise ValueError(f"models dir {self.root!r} does not exist")
        self.budget_bytes = int(config.model_budget_mb * (1 << 20))
        self.warm_buckets = (
            ladder(config.max_queue_rows) if warm else ()
        )
        self.fleet = MetricsRegistry()
        for name in _FLEET_COUNTERS:
            self.fleet.counter(name)
        self._lock = threading.Lock()
        # serializes admission + eviction sequences: two concurrent
        # admissions racing the budget would otherwise both evict
        self._admit_lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        self._closed = False
        # fleet-wide max feature width, maintained at admission/
        # discovery and refreshed from the live stores at most once per
        # _NF_TTL_S (a hot reload can WIDEN a tenant's model without
        # re-admission) — the per-request body bound reads one integer,
        # not O(tenants) lock work per POST.  Monotone: an eviction or
        # narrowing reload keeps the high-water mark, which only makes
        # the reject-before-read bound more permissive, never wrong.
        self._max_nf = 0
        self._nf_refreshed = 0.0
        names = self._refresh_discovery()
        if not names:
            raise ValueError(
                f"no exported bundles under {self.root!r} — each tenant "
                "is an immediate subdirectory holding an export bundle"
            )
        # the device thread spawns only AFTER discovery validated: a
        # ctor that raises above must not leak a parked daemon thread
        # per failed construction attempt (supervisor retry loops)
        self.scheduler = DeviceScheduler()
        # eager admission in name order until the budget stops fitting:
        # tenants that fit are warm before the first request; the rest
        # stay cold and admit on demand.  A corrupt bundle refuses ONLY
        # its tenant — a fleet of hundreds must not fail-fast on one.
        for name in names:
            with self._lock:
                t = self._tenants[name]
            cost = self._bundle_cost(t.dir)
            with self._lock:
                if (self.budget_bytes
                        and self._admitted_bytes_locked() + cost
                        > self.budget_bytes):
                    continue
            try:
                self._admit(name, cost=cost)
            except Exception as e:
                log.error("startup admission of %s refused: %s", name, e)

    # ---- discovery ----
    def _is_bundle(self, path: str) -> bool:
        return (os.path.isfile(os.path.join(path, NATIVE_MANIFEST))
                or os.path.isfile(os.path.join(path, NATIVE_WEIGHTS)))

    def _bundle_num_features(self, bundle_dir: str) -> int:
        """Feature width read off the bundle's arch file WITHOUT loading
        the model — keeps the fleet-wide body bound honest for tenants
        that are discovered but not (yet) admitted."""
        try:
            with open(os.path.join(bundle_dir, NATIVE_ARCH)) as f:
                return int(json.load(f).get("num_features", 0))
        except (OSError, ValueError, TypeError):
            return 0

    def _refresh_discovery(self) -> list[str]:
        """Scan the models dir for tenant subdirectories: new ones gain
        cold records (a bundle dropped in after startup becomes
        admittable without a restart), and unadmitted records whose
        directory no longer holds a bundle are PRUNED — a deleted
        tenant must go back to 404, not haunt /models and burn a disk
        admission attempt per holddown window.  Admitted tenants keep
        serving from memory regardless of what happened on disk (their
        own reload poller reports the missing artifact).  Returns the
        sorted known names.

        All filesystem work runs OUTSIDE the store lock (a hung
        network-mounted models dir must never stall the scoring fast
        path, which takes the same lock); the map merge under the lock
        is pure memory."""
        try:
            entries = sorted(os.listdir(self.root))
        except OSError as e:
            log.error("cannot list models dir %s: %s", self.root, e)
            entries = []
        live: dict[str, str] = {}  # name -> path, disk-verified
        for name in entries:
            path = os.path.join(self.root, name)
            if (_NAME_OK.match(name) and os.path.isdir(path)
                    and self._is_bundle(path)):
                live[name] = path
        with self._lock:
            known = set(self._tenants)
        new_nf = 0
        for name in live:
            if name not in known:
                new_nf = max(new_nf,
                             self._bundle_num_features(live[name]))
        with self._lock:
            for name, path in live.items():
                if name not in self._tenants:
                    self._tenants[name] = _Tenant(name, path)
            for name in list(self._tenants):
                t = self._tenants[name]
                if (name not in live
                        and t.state in ("cold", "refused")
                        and t.admit_event is None):
                    del self._tenants[name]
            self._max_nf = max(self._max_nf, new_nf)
            return sorted(self._tenants)

    def _bundle_cost(self, bundle_dir: str) -> int:
        """Bundle bytes as the admission cost: every file under the
        bundle directory, RECURSIVELY — a SavedModel export keeps its
        weights in a ``variables/`` subdirectory, and skipping subdirs
        would under-count exactly the bytes that become resident model
        memory.  A stable proxy for the admission budget."""
        total = 0
        try:
            for root, _dirs, files in os.walk(bundle_dir):
                for f in files:
                    try:
                        total += os.path.getsize(os.path.join(root, f))
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    # ---- resolution ----
    def _sole_locked(self) -> _Tenant | None:
        """The unambiguous tenant legacy ``/score`` resolves to:
        exactly one known, else exactly one admitted.  Caller holds the
        lock.  ONE home for this rule — routing and shed attribution
        must never disagree on which model an unnamed request meant."""
        if len(self._tenants) == 1:
            return next(iter(self._tenants.values()))
        admitted = [t for t in self._tenants.values()
                    if t.state == "admitted"]
        return admitted[0] if len(admitted) == 1 else None

    def _resolve(self, name: str | None) -> _Tenant:
        """Name → tenant record, creating/pruning records from targeted
        disk checks as needed.  Disk I/O happens OUTSIDE the store lock
        — only map reads/writes run under it, so a slow models mount
        can't stall requests for admitted tenants."""
        with self._lock:
            if name is None:
                t = self._sole_locked()
                if t is not None:
                    return t
                raise AmbiguousModel(
                    f"{len(self._tenants)} models are served here; "
                    "POST /score/<model>"
                )
            t = self._tenants.get(name)
            check_gone = (t is not None and t.admit_event is None
                          and t.state in ("cold", "refused"))
        if t is not None:
            if check_gone and not self._is_bundle(t.dir):
                # the bundle was deleted out from under an unadmitted
                # record: back to 404, not a doomed 503 admission loop
                with self._lock:
                    cur = self._tenants.get(name)
                    if (cur is t and t.admit_event is None
                            and t.state in ("cold", "refused")):
                        del self._tenants[name]
                        self.fleet.inc("unknown_model_total")
                        raise UnknownModel(name)
                    t = cur
                if t is None:
                    raise UnknownModel(name)
            return t
        # unknown: one targeted disk check so a bundle published after
        # the last scan is admittable without waiting for a rescan
        path = os.path.join(self.root, name)
        if not (_NAME_OK.match(name) and os.path.isdir(path)
                and self._is_bundle(path)):
            self.fleet.inc("unknown_model_total")
            raise UnknownModel(name)
        nf = self._bundle_num_features(path)
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = _Tenant(name, path)
                self._tenants[name] = t
            # the body bound must see this tenant's width BEFORE its
            # first (possibly large) request admits it
            self._max_nf = max(self._max_nf, nf)
            return t

    def acquire(self, name: str | None, wait_s: float | None = None):
        """The routable tenant for ``name`` (or the unambiguous tenant
        for legacy ``/score``), admitted on demand and LRU-touched.
        Raises :class:`UnknownModel`, :class:`AmbiguousModel`,
        :class:`ModelColdStart` (admission still running past the
        guard), or :class:`AdmissionRefused`."""
        if wait_s is None:
            wait_s = self.config.model_admit_wait_s
        deadline = time.monotonic() + wait_s
        while True:
            t = self._resolve(name)
            with self._lock:
                if self._closed:
                    raise AdmissionRefused("store is draining")
                if t.state == "admitted":
                    t.last_used = time.monotonic()
                    return t
                if (t.state == "refused"
                        and time.monotonic() - t.refused_at
                        < _REFUSAL_HOLDDOWN_S):
                    raise AdmissionRefused(t.error or "admission refused")
                if t.admit_event is None:
                    # single-flight: the first cold-start request spawns
                    # the admission; everyone else shares its event
                    t.admit_event = threading.Event()
                    threading.Thread(
                        target=self._admit_bg,
                        args=(t.name, t.admit_event),
                        name=f"serve-admit-{t.name}", daemon=True,
                    ).start()
                ev = t.admit_event
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not ev.wait(remaining):
                self.fleet.inc("cold_start_timeouts_total")
                raise ModelColdStart(t.name)
            # read the state the admission WE waited on left behind: a
            # refusal surfaces as the refusal, not as another spin of
            # the admission loop
            with self._lock:
                if t.state == "admitted":
                    t.last_used = time.monotonic()
                    return t
                if t.state == "refused":
                    raise AdmissionRefused(
                        t.error or "admission refused")
            # else: evicted again already (budget thrash) — loop

    # ---- admission ----
    def _admit_bg(self, name: str, ev: threading.Event) -> None:
        try:
            self._admit(name)
        except Exception:
            pass  # recorded on the tenant by _admit
        finally:
            # set the event the WAITERS hold, not one re-looked-up from
            # the map: a discovery prune can orphan the record between
            # spawn and here, and a never-set event would hang every
            # waiter for the full cold-start guard instead of letting
            # them loop into a prompt 404
            with self._lock:
                t = self._tenants.get(name)
                if t is not None and t.admit_event is ev:
                    t.admit_event = None
            ev.set()

    def _make_score_fn(self, t: _Tenant, store: ModelStore):
        def score(rows):
            from shifu_tensorflow_tpu.export.eval_model import (
                ModelReleasedError,
            )

            # the STORE is bound into the closure (not read off the
            # tenant record): an eviction's drain keeps dispatching
            # through this fn after the tenant is unrouted, and the
            # dispatch must reach the store it was admitted with
            for attempt in (0, 1):
                loaded = store.current()
                try:
                    return loaded.model.compute_batch(rows)
                except ModelReleasedError:
                    if attempt:
                        raise
                    batcher = t.batcher
                    obs_journal.emit(
                        "model_released_retry", plane="serve",
                        model=t.name,
                        rids=(batcher.dispatching_rids()
                              if batcher is not None else []),
                        old_epoch=loaded.epoch,
                    )
            raise AssertionError("unreachable")

        return score

    def _admitted_bytes_locked(self) -> int:
        return sum(t.cost_bytes for t in self._tenants.values()
                   if t.state == "admitted")

    def _admit(self, name: str, cost: int | None = None) -> _Tenant:
        """Synchronous verify→warm→register admission, evicting LRU
        tenants as the budget requires.  Runs under the admission lock;
        requests for already-admitted tenants never touch it.
        ``cost`` lets the startup fit-check pass its already-scanned
        bundle size instead of re-statting the directory."""
        with self._admit_lock:
            with self._lock:
                t = self._tenants.get(name)
                if t is None:
                    # pruned between spawn and here (bundle deleted);
                    # the waiter's re-resolve turns this into a 404
                    raise AdmissionRefused(
                        f"tenant {name!r} disappeared before admission")
                if t.state == "admitted":
                    return t
                t.state = "admitting"
            t0 = time.monotonic()
            try:
                if cost is None:
                    cost = self._bundle_cost(t.dir)
                if self.budget_bytes and cost > self.budget_bytes:
                    raise AdmissionRefused(
                        f"bundle is {cost} bytes, over the whole "
                        f"{self.budget_bytes}-byte budget"
                    )
                # LRU eviction until the newcomer fits
                while self.budget_bytes:
                    with self._lock:
                        if (self._admitted_bytes_locked() + cost
                                <= self.budget_bytes):
                            break
                        victims = [x for x in self._tenants.values()
                                   if x.state == "admitted"]
                        victim = (min(victims, key=lambda x: x.last_used)
                                  if victims else None)
                    if victim is None:
                        raise AdmissionRefused(
                            f"budget cannot fit {name} and nothing is "
                            "evictable"
                        )
                    self._evict(victim, reason="budget")
                # per-tenant metrics are created ONCE and survive
                # evict→re-admit cycles: counters must stay monotonic
                # for scrapers, and the drain summary must not forget a
                # tenant's pre-eviction traffic
                metrics = (t.metrics if t.metrics is not None
                           else ServeMetrics())
                # the full verify-before-admit chain AND the warm ladder
                # run inside this constructor — the model is not
                # routable until both passed
                store = ModelStore(
                    t.dir,
                    backend=self.config.backend,
                    poll_interval_s=self.config.reload_poll_ms / 1000.0,
                    metrics=metrics,
                    warm_buckets=self.warm_buckets,
                    model_name=name,
                )
                store.start()  # per-tenant hot-reload poller
                try:
                    batcher = MicroBatcher(
                        self._make_score_fn(t, store),
                        max_batch=self.config.max_batch,
                        max_delay_s=self.config.max_delay_ms / 1000.0,
                        max_queue_rows=self.config.max_queue_rows,
                        retry_after_s=self.config.retry_after_s,
                        metrics=metrics,
                        scheduler=self.scheduler,
                        model=name,
                        weight=self.config.weight_for(name),
                        lane=self.lane,
                    )
                except BaseException:
                    # a failure PAST the store construction (e.g. the
                    # scheduler closed under a racing shutdown) must not
                    # leak the fully loaded model + its poller thread
                    store.close()
                    raise
            except Exception as e:
                with self._lock:
                    t.state = "refused"
                    t.error = f"{type(e).__name__}: {e}"
                    t.refused_at = time.monotonic()
                self.fleet.inc("admit_failures_total")
                obs_journal.emit("model_admit_failed", plane="serve",
                                 model=name, why=str(e))
                log.error("admission of %s refused: %s", name, e)
                raise
            now = time.monotonic()
            try:
                nf = int(store.current().model.num_features)
            except Exception:
                nf = 0
            with self._lock:
                t.store, t.batcher, t.metrics = store, batcher, metrics
                t.cost_bytes = cost
                t.state = "admitted"
                t.error = None
                t.admitted_at = t.last_used = now
                self._max_nf = max(self._max_nf, nf)
            self.fleet.inc("admissions_total")
            wd = obs_slo.active()
            if wd is not None:
                wd.track_serve_tenant(name)
            # device-memory accounting (obs/memory.py): admission is the
            # serve plane's snapshot cadence — the journaled device_mem
            # and stpu_devmem_model_bytes gauge show what each tenant
            # holds ON DEVICE (the LRU budget above counts bundle bytes
            # on disk; a quantized or host-offloaded model's device
            # footprint can differ several-fold)
            device_bytes = self._devmem_snapshot(event_model=name)
            obs_journal.emit(
                "model_admit", plane="serve", model=name,
                cost_bytes=cost, admit_ms=round((now - t0) * 1000.0, 1),
                device_bytes=device_bytes.get(name, 0),
                digest=store.current().digest[:12],
                verified=store.current().verified,
                # bundles shipping AOT executables admit by deserialize:
                # the warm ladder's per-bucket aot_load/aot_fallback
                # split, absent for pre-AOT bundles (schema parity)
                **_aot_fields(store.current().model),
            )
            log.info("admitted model %s (%d bytes, %.0f ms)",
                     name, cost, (now - t0) * 1000.0)
            return t

    def _devmem_snapshot(self, event_model: str | None = None,
                         **ctx) -> dict[str, int]:
        """One device-memory accounting pass over every admitted tenant
        (obs/memory.py): per-model device bytes journaled as
        ``device_mem`` and exported as ``stpu_devmem_model_bytes``
        gauges.  Returns {model: device_bytes}.  Never raises and never
        holds the store lock across the accounting walk — admission and
        eviction call this on their (rare) transitions."""
        mem = obs_memory.active()
        if mem is None:
            return {}
        with self._lock:
            admitted = [(t.name, t.store)
                        for t in self._tenants.values()
                        if t.state == "admitted" and t.store is not None]
        models: dict[str, int] = {}
        for name, store in admitted:
            try:
                models[name] = store.current().model.device_bytes()
            except Exception:
                continue  # racing evict/reload: skip, not fail
        try:
            mem.snapshot(models=models,
                         **({"model": event_model} if event_model else {}),
                         **ctx)
        except Exception as e:
            log.warning("device-memory snapshot failed: %s: %s",
                        type(e).__name__, e)
        return models

    # ---- eviction ----
    def _evict(self, t: _Tenant, reason: str) -> None:
        """Unroute, drain, release.  The tenant record survives — a
        later request re-admits it on demand.

        Ordering matters: the state flips to ``cold`` FIRST (acquire
        stops routing here), but ``t.store``/``t.batcher`` stay set
        until the drain completes — the drain dispatches every queued
        batch through the tenant's score_fn, and a request that raced
        the eviction must finish (or get a typed BatcherClosed it can
        retry), never an AttributeError on a nulled reference."""
        with self._lock:
            if t.state != "admitted":
                return
            t.state = "cold"  # unroutable from here on
            store, batcher = t.store, t.batcher
            idle_s = time.monotonic() - t.last_used
            freed = t.cost_bytes
        # drain OUTSIDE the locks the request path takes: in-flight
        # dispatches for this tenant finish (the pack thread drains its
        # scheduler queue and unregisters), then the model releases
        # through EvalModel's compute lock — never under a running score
        batcher.close(drain=True)
        store.close()
        with self._lock:
            t.store = t.batcher = None
            t.cost_bytes = 0
        wd = obs_slo.active()
        if wd is not None:
            # the tenant's SLO gauges leave the scrape with it — a
            # frozen last-known p99 for a model that isn't serving
            # would mislead the autoscaler these gauges exist for
            wd.untrack_serve_tenant(t.name)
        mem = obs_memory.active()
        if mem is not None:
            mem.drop_model(t.name)
        self.fleet.inc("evictions_total")
        obs_journal.emit("model_evict", plane="serve", model=t.name,
                         reason=reason, freed_bytes=freed,
                         idle_s=round(idle_s, 3))
        # post-release snapshot: the device_mem event after an eviction
        # is the proof the bytes actually left the device (a leaked
        # reference shows up as `other` growing by exactly this model)
        self._devmem_snapshot(event_model=t.name, reason=reason)
        log.info("evicted model %s (%s, freed %d bytes, idle %.1fs)",
                 t.name, reason, freed, idle_s)

    # ---- reading ----
    def models(self, rescan: bool = True) -> dict:
        """Per-tenant detail for ``/models`` (``rescan=True``: pick up
        bundles dropped in after startup) and ``/healthz``
        (``rescan=False``: a load balancer probing every second must
        not pay O(entries) disk syscalls per probe — a new tenant still
        appears at its first ``/models`` hit or scoring request)."""
        if rescan:
            self._refresh_discovery()
        depths = self.scheduler.queue_depths()
        out: dict[str, dict] = {}
        with self._lock:
            tenants = sorted(self._tenants.items())
        for name, t in tenants:
            info: dict = {
                "state": t.state,
                "weight": self.config.weight_for(name),
            }
            if t.state == "admitted" and t.store is not None:
                try:
                    m = t.store.current()
                except Exception:  # racing an eviction
                    info["state"] = "cold"
                    out[name] = info
                    continue
                info.update({
                    "model_epoch": m.epoch,
                    "model_digest": m.digest[:12],
                    "model_verified": m.verified,
                    "cost_bytes": t.cost_bytes,
                    "queue_rows": (t.batcher.queued_rows()
                                   if t.batcher is not None else 0),
                    "queued_batches": depths.get(name, 0),
                    "idle_s": round(
                        max(0.0, time.monotonic() - t.last_used), 1),
                })
            elif t.state == "refused":
                info["error"] = t.error
            out[name] = info
        return out

    def peek(self, name: str) -> _Tenant | None:
        """The tenant record without admission or LRU touch (shed
        bookkeeping), or None when unknown."""
        with self._lock:
            return self._tenants.get(name)

    def retire(self, name: str) -> bool:
        """Operator/lifecycle-initiated eviction by name: drain and
        release ``name`` if admitted (the ``_evict`` path with a
        ``retire`` reason, so the journal distinguishes a deliberate
        retirement from budget pressure).  A cold or unknown tenant is
        already retired — no-op, False.  The tenant record survives, so
        a stray request re-admits from whatever bundle the directory
        now holds (after a promotion: the promoted generation)."""
        with self._lock:
            t = self._tenants.get(name)
        if t is None or t.state != "admitted":
            return False
        self._evict(t, reason="retire")
        return True

    def refresh_tenant(self, name: str) -> bool:
        """Targeted single-name discovery — one disk check, no full
        models-dir rescan (the /healthz/<model> miss path; a balancer
        probing a dead name must not cost O(models) stats per probe).
        True when the tenant is (now) known."""
        try:
            self._resolve(name)
            return True
        except (UnknownModel, AmbiguousModel):
            return False

    def sole(self) -> _Tenant | None:
        """The unambiguous legacy-``/score`` tenant or None — shed
        bookkeeping for unnamed requests reads this so the journal can
        still say WHICH model shed (same rule as routing, one home)."""
        with self._lock:
            return self._sole_locked()

    def admitted(self) -> list[str]:
        with self._lock:
            return sorted(n for n, t in self._tenants.items()
                          if t.state == "admitted")

    def aggregate_counters(self) -> dict[str, int]:
        """Every tenant's counters summed — the CLI's final stopped line
        and the supervisor's fleet aggregate read this (tenant metrics
        survive eviction, so a drained fleet still reports its totals)."""
        totals: dict[str, int] = {}
        with self._lock:
            tenants = list(self._tenants.values())
        for t in tenants:
            if t.metrics is None:
                continue
            for k, v in t.metrics.counters().items():
                totals[k] = totals.get(k, 0) + v
        return totals

    def per_tenant_counters(self) -> dict[str, dict[str, int]]:
        """Every tenant's monotonic counters, per tenant — the rollup
        compactor's counter source (obs/rollup.py): rate-limited shed
        EVENTS can undercount in the journal, these cannot.  Tenant
        metrics are created once and survive eviction (the PR-9
        monotonicity rule), so the series never reset mid-run."""
        with self._lock:
            tenants = list(self._tenants.items())
        return {name: t.metrics.counters() for name, t in tenants
                if t.metrics is not None}

    #: how stale the feature-width high-water mark may run before the
    #: next request re-reads the live stores (a hot reload that widened
    #: a model becomes visible to the body bound within this window)
    _NF_TTL_S = 5.0

    def max_num_features(self) -> int:
        """High-water mark of known models' feature widths — the
        server's reject-before-read body bound.  One integer read on
        the request path; at most once per ``_NF_TTL_S`` it re-reads
        the admitted stores so a reload-widened model isn't 413'd below
        what its own single-model server would accept.  0 before any
        discovery (the caller floors it)."""
        now = time.monotonic()
        if now - self._nf_refreshed < self._NF_TTL_S:
            return self._max_nf
        self._nf_refreshed = now
        with self._lock:
            stores = [t.store for t in self._tenants.values()
                      if t.state == "admitted" and t.store is not None]
        nf = self._max_nf
        for store in stores:
            try:
                nf = max(nf, store.current().model.num_features)
            except Exception:
                pass
        self._max_nf = nf
        return nf

    def metrics_text(self, unrouted=None) -> str:
        """Fleet gauges + every admitted tenant's registry rendered with
        its ``model`` label — the per-model dimension on every
        ``stpu_serve_*`` series.  ``unrouted`` is the server's
        pre-resolution ServeMetrics, rendered under ``model="_unrouted"``
        and merged here so the whole serve block regroups into ONE
        ``# TYPE`` line per metric family with contiguous samples — a
        naive concat repeats the TYPE line per tenant, which strict
        exposition-format parsers reject outright."""
        with self._lock:
            known = len(self._tenants)
            admitted = [(n, t) for n, t in sorted(self._tenants.items())
                        if t.state == "admitted"]
            admitted_bytes = self._admitted_bytes_locked()
        self.fleet.set_gauge("models_known", known)
        self.fleet.set_gauge("models_admitted", len(admitted))
        self.fleet.set_gauge("budget_bytes", self.budget_bytes)
        self.fleet.set_gauge("admitted_bytes", admitted_bytes)
        # device-level occupancy across every tenant this scheduler has
        # dispatched — on the lane owner this is the FLEET number the
        # shared-lane gate reads (siblings' forwards coalesce here)
        self.fleet.set_gauge("occupancy", self.scheduler.occupancy())
        parts = [self.fleet.render_prometheus("stpu_serve_fleet_")]
        for name, t in admitted:
            metrics, store, batcher = t.metrics, t.store, t.batcher
            if metrics is None or store is None or batcher is None:
                continue  # racing an eviction
            try:
                m = store.current()
                epoch, digest, verified = m.epoch, m.digest[:12], m.verified
            except Exception:
                epoch, digest, verified = -1, "", False
            parts.append(metrics.render_prometheus(
                queue_rows=batcher.queued_rows(),
                model_epoch=epoch,
                model_digest=digest,
                model_verified=verified,
                extra_labels=f'model="{name}"',
            ))
        if unrouted is not None:
            parts.append(unrouted.registry.render_prometheus(
                "stpu_serve_", extra_labels='model="_unrouted"'))
        return _merge_exposition(parts)

    # ---- lifecycle ----
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        with self._admit_lock:
            with self._lock:
                admitted = [t for t in self._tenants.values()
                            if t.state == "admitted"]
            for t in admitted:
                self._evict(t, reason="shutdown")
        self.scheduler.close()


# ---- batch admission (bulk scoring plane) --------------------------------

def discover_bundles(models_dir: str) -> dict[str, str]:
    """Tenant name → bundle dir, by the SAME rules the serving store
    routes by (immediate subdirectories, ``_NAME_OK`` charset, bundle
    marker files) — a tenant the batch scorer scores is a tenant the
    HTTP plane would serve.  A ``models_dir`` that is ITSELF a bundle
    (single-model export) discovers as one tenant named ``default``."""
    if (os.path.isfile(os.path.join(models_dir, NATIVE_MANIFEST))
            or os.path.isfile(os.path.join(models_dir, NATIVE_WEIGHTS))):
        return {"default": models_dir}
    out: dict[str, str] = {}
    try:
        names = sorted(os.listdir(models_dir))
    except OSError:
        return out
    for name in names:
        path = os.path.join(models_dir, name)
        if (_NAME_OK.match(name) and os.path.isdir(path)
                and (os.path.isfile(os.path.join(path, NATIVE_MANIFEST))
                     or os.path.isfile(os.path.join(path, NATIVE_WEIGHTS)))):
            out[name] = path
    return out


def admit_batch_tenants(
    models_dir: str,
    *,
    backend: str = "native",
    tenants: list[str] | None = None,
    retry_policy=None,
) -> dict[str, ModelStore]:
    """Admit every tenant for BATCH scoring: the full verify-before-admit
    chain (manifest digests → EvalModel → AOT deserialization) with NONE
    of the serving machinery — no reload poller (``.start()`` is never
    called), no batcher, no scheduler, no budget/LRU.  A scan worker
    admits, scores its leased shards, and exits; PR-14 AOT bundles make
    this admission-free in the compile sense (~ms per bucket), which is
    what lets the scan fleet treat workers as disposable.

    ``tenants`` restricts (and validates) the set; admission failures
    raise — a bulk job scoring N tenants must not silently score N-1.
    Callers own the stores' lifecycle: ``close()`` each when done."""
    found = discover_bundles(models_dir)
    if tenants is not None:
        missing = sorted(set(tenants) - set(found))
        if missing:
            raise ValueError(
                f"tenant bundle(s) not found under {models_dir!r}: "
                f"{missing} (have: {sorted(found)})")
        found = {name: found[name] for name in tenants}
    if not found:
        raise ValueError(f"no export bundles under {models_dir!r}")
    out: dict[str, ModelStore] = {}
    try:
        for name in sorted(found):
            out[name] = ModelStore(
                found[name],
                backend=backend,
                poll_interval_s=0.0,  # batch: no hot reload
                retry_policy=retry_policy,
                warm_buckets=(),      # compute_batch pads per call
                model_name=name,
            )
    except Exception:
        for store in out.values():
            try:
                store.close()
            except Exception:
                pass
        raise
    return out
