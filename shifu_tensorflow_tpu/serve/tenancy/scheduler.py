"""Shared device scheduler: weighted deficit round-robin over per-tenant
dispatch queues.

The device (one jitted scorer per tenant, all funneling through one
process's XLA client) is a single serialized resource — EvalModel's
documented contract is one scoring thread.  With one micro-batcher per
tenant, each tenant's pack/scatter host work stays parallel, but their
*dispatches* must be arbitrated in one place or a hot tenant's backlog
simply occupies the device in arrival order and every other tenant's
latency rides on it.  That arbitration is deficit round-robin (Shreedhar
& Varghese, SIGCOMM '95) with per-tenant weights:

- each tenant queue holds packed batches (``_Work``) in FIFO order;
- the device thread visits tenant queues round-robin; a visited queue
  with backlog earns ``quantum × weight`` deficit ROWS per pass and may
  dispatch while its deficit covers the head batch's *bucket* size (the
  padded row count — what the device actually pays, so a tenant cannot
  launder cost through padding);
- an emptied queue forfeits its deficit (the classic DRR rule: credit
  never accumulates while idle, so a returning tenant gets fairness,
  not a stored burst).

Long-run device rows are therefore shared proportionally to weight
among backlogged tenants, with single-batch granularity — one tenant at
sustained overload delays another's dispatch by at most the in-flight
batch plus its own next quantum, which is the p99-isolation property
``tests/test_tenancy.py`` and ``BENCH_SERVE_TENANTS.json`` pin.  When
only one tenant has work it gets the whole device: work-conserving, no
reserved idle shares.

Weights come from ``shifu.tpu.serve-tenant-weight-<model>`` (default 1);
the quantum is rows per visit — small enough to interleave tenants
between batches, large enough that a typical coalesced batch clears in
one or two visits.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.utils import logs

log = logs.get("serve.sched")

#: deficit rows granted per round-robin visit (× tenant weight).  The
#: smallest ladder bucket: a weight-1 tenant then bursts at most ONE
#: minimum-size batch per pass, which is the tightest latency isolation
#: the batch granularity allows (a bigger quantum lets a hot tenant
#: dispatch quantum/8 small batches back-to-back while a victim waits).
#: Larger buckets accumulate credit over several passes — each pass is
#: lock-held arithmetic, microseconds against millisecond dispatches.
DEFAULT_QUANTUM_ROWS = 8


class _TenantQueue:
    """One tenant's dispatch queue + DRR state.  All fields are guarded
    by the scheduler's condition lock except the batcher reference."""

    __slots__ = ("name", "weight", "batcher", "work", "deficit",
                 "in_flight", "registered", "dispatched_rows",
                 "dispatched_bucket_rows", "dispatched_batches",
                 "dispatched_device_s")

    def __init__(self, name: str, batcher, weight: float):
        self.name = name
        self.weight = float(weight)
        self.batcher = batcher
        self.work: deque = deque()
        self.deficit = 0.0
        self.in_flight = False   # device thread is inside this tenant's
        #                          score_fn right now
        self.registered = True
        self.dispatched_rows = 0
        # bucket (padded) rows actually paid to the device — the
        # denominator of occupancy; rows/bucket_rows < 1 means the
        # ladder padded, fleet fragmentation makes it fall further
        self.dispatched_bucket_rows = 0
        self.dispatched_batches = 0
        # device-seconds this tenant's dispatches consumed (the
        # batcher's dispatch_s, accumulated here so the scheduler's own
        # totals answer "who got the device" without the obs plane)
        self.dispatched_device_s = 0.0


class DeviceScheduler:
    """The one device dispatch thread shared by every tenant batcher.

    Lifecycle: ``register`` (MicroBatcher ctor in scheduler mode) →
    ``submit`` (the tenant's pack thread) → the device thread calls the
    owning batcher's ``_dispatch_one`` (which scores and feeds that
    batcher's scatter queue) → ``drain``/``unregister`` (the pack
    thread's shutdown path, so an evicted tenant leaves no orphaned
    work).  ``close`` stops the device thread after the queues empty.
    """

    def __init__(self, quantum_rows: int = DEFAULT_QUANTUM_ROWS):
        if quantum_rows < 1:
            raise ValueError("quantum_rows must be >= 1")
        self.quantum_rows = int(quantum_rows)
        self._cond = threading.Condition()
        self._tenants: dict[int, _TenantQueue] = {}  # id(handle) keyed
        self._order: list[_TenantQueue] = []         # round-robin ring
        self._rr = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._device_loop, name="serve-device", daemon=True)
        self._thread.start()

    # ---- tenant side ----
    def register(self, name: str, batcher, weight: float = 1.0):
        """Add a tenant queue; returns the handle ``submit``/``drain``/
        ``unregister`` take.  Weight must be positive — a zero weight
        could never afford any batch and would wedge its own queue."""
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        tq = _TenantQueue(name, batcher, weight)
        with self._cond:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            self._tenants[id(tq)] = tq
            self._order.append(tq)
        return tq

    #: per-tenant scheduler-queue depth the pack thread may stage ahead.
    #: BOUNDED handoff is what preserves shed-before-queue: an unbounded
    #: queue here would let the pack thread drain the whole admission
    #: queue into the scheduler and the admission bound would never
    #: overflow — overload would become invisible latency again.  2
    #: mirrors the single-model pipeline depth (one staged + one ahead),
    #: keeping the documented in-flight bound at "admission queue + a
    #: few coalesced batches" per tenant.
    MAX_STAGED = 2

    def submit(self, handle: _TenantQueue, work) -> None:
        """Stage one packed batch; BLOCKS (the tenant's pack thread)
        while the tenant already has MAX_STAGED batches waiting — the
        backpressure that keeps rows countable in the tenant's admission
        queue, where the shed bound can see them."""
        with self._cond:
            while (len(handle.work) >= self.MAX_STAGED
                   and handle.registered and not self._closed):
                self._cond.wait()
            handle.work.append(work)
            self._cond.notify_all()

    def drain(self, handle: _TenantQueue, timeout_s: float = 20.0) -> bool:
        """Block until every batch this tenant submitted has been
        dispatched (its results are already in the tenant's scatter
        queue when this returns — ``in_flight`` clears only after
        ``_dispatch_one`` completes).  Bounded: a wedged scorer must not
        hang an eviction forever — on timeout the tenant unregisters
        anyway and the straggler work's results are dropped.  The
        default stays UNDER MicroBatcher.close()'s 30 s thread join so
        the eviction path observes the drain verdict (success or
        give-up) before the batcher's close returns and the model is
        released — a longer drain here would silently outlive the join
        and the release would race the still-queued batches."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while handle.work or handle.in_flight:
                self._cond.wait(timeout=1.0)
                if (time.monotonic() > deadline
                        and (handle.work or handle.in_flight)):
                    log.warning(
                        "drain of tenant %s timed out with %d batches "
                        "queued", handle.name, len(handle.work))
                    return False
        return True

    def unregister(self, handle: _TenantQueue) -> list:
        """Remove the tenant queue; returns any batches still staged
        (non-empty only after a drain timeout) so the caller can FAIL
        their waiters — silently dropping them would leave every caller
        blocked until its own submit timeout."""
        with self._cond:
            handle.registered = False
            self._tenants.pop(id(handle), None)
            if handle in self._order:
                self._order.remove(handle)
            leftovers = list(handle.work)
            handle.work.clear()
            self._cond.notify_all()
        return leftovers

    def set_weight(self, handle_or_name, weight: float) -> float:
        """Runtime tenant-weight adjustment — the lifecycle ramp's
        actuator.  Takes the handle ``register`` returned or the tenant
        NAME (the ramp controller only knows names); returns the
        previous weight.  Journaled as ``weight_change`` so a ramp step
        is reconstructable from a dead fleet's files like every other
        transition.

        Coordinator-free by design: mutating the DRR weight under the
        scheduler lock re-shares device rows from the very next ring
        pass, so a small ramp step does not pay a rolling restart.  The
        restart path stays for *worker-visible config* (the persisted
        ``serve-tenant-weight-*`` keys new workers resolve at boot) —
        this setter moves live traffic, the config the fleet converges
        to on its next roll."""
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        with self._cond:
            tq = None
            if isinstance(handle_or_name, _TenantQueue):
                if handle_or_name.registered:
                    tq = handle_or_name
            else:
                for cand in self._order:
                    if cand.name == handle_or_name:
                        tq = cand
                        break
            if tq is None:
                raise KeyError(f"no registered tenant {handle_or_name!r}")
            before = tq.weight
            tq.weight = float(weight)
            # an idle queue's stale deficit is already forfeited on
            # visit; an accumulating one re-earns at the new rate from
            # the next pass — no retroactive credit either way
            self._cond.notify_all()
        if obs_journal.active() is not None:
            obs_journal.emit("weight_change", plane="serve",
                             model=tq.name, weight=float(weight),
                             weight_before=before)
        return before

    # ---- reading ----
    def queue_depths(self) -> dict[str, int]:
        """Tenant name → queued (undispatched) batches, for /healthz."""
        with self._cond:
            return {tq.name: len(tq.work) for tq in self._order}

    def dispatch_totals(self) -> dict[str, dict]:
        with self._cond:
            return {
                tq.name: {"rows": tq.dispatched_rows,
                          "bucket_rows": tq.dispatched_bucket_rows,
                          "batches": tq.dispatched_batches,
                          "device_s": round(tq.dispatched_device_s, 6),
                          "weight": tq.weight}
                for tq in self._order
            }

    def occupancy(self) -> float:
        """Useful rows as a fraction of DISPATCHED (bucket) rows, across
        every tenant this scheduler has ever served — the fleet-level
        reading when this is the lane owner's scheduler.  1.0 when idle
        (no dispatch yet means no padding waste yet)."""
        with self._cond:
            rows = sum(tq.dispatched_rows for tq in self._order)
            bucket = sum(tq.dispatched_bucket_rows for tq in self._order)
        return round(rows / bucket, 6) if bucket else 1.0


    # ---- device thread ----
    def _pick_locked(self) -> _TenantQueue | None:
        """Deficit round-robin: returns the tenant whose head batch to
        dispatch next, having already charged its deficit.  Caller holds
        the lock and guarantees at least one queue is non-empty.

        Terminates because every full ring pass grants quantum×weight>0
        to each backlogged tenant, so some deficit eventually covers its
        head bucket."""
        n = len(self._order)
        while True:
            tq = self._order[self._rr % n]
            if not tq.work:
                # idle queues forfeit credit (DRR: no stored bursts)
                tq.deficit = 0.0
                self._rr += 1
                continue
            cost = tq.work[0].bucket
            if tq.deficit >= cost:
                # affordable: serve and STAY on this tenant (the next
                # pick re-visits it and serves while the deficit lasts —
                # bursts are bounded by quantum×weight rows per pass).
                # Draining the queue does NOT forfeit the remainder: the
                # staged handoff is shallow (MAX_STAGED) and the pack
                # thread refills it mid-dispatch, so a backlogged tenant
                # must keep its leftover credit or its weight advantage
                # would reset every other batch.  A tenant found empty
                # at VISIT time (truly idle) forfeits above — the
                # classic DRR no-stored-bursts rule.
                tq.deficit -= cost
                if len(tq.work) == 1:
                    self._rr += 1
                return tq
            # can't afford the head batch: grant this visit's quantum
            # and move on — the credit accumulates across ring passes
            # until the batch clears (large buckets take several)
            tq.deficit += self.quantum_rows * tq.weight
            self._rr += 1

    def _device_loop(self) -> None:
        while True:
            with self._cond:
                while (not self._closed
                       and not any(tq.work for tq in self._order)):
                    self._cond.wait()
                if self._closed and not any(
                        tq.work for tq in self._order):
                    return
                tq = self._pick_locked()
                work = tq.work.popleft()
                tq.in_flight = True
            try:
                # outside the lock: scoring must not serialize the
                # tenants' pack/scatter threads or submissions.  The
                # lane's busy/idle split is the COST ACCOUNTANT's job
                # (obs/cost.py note_busy, fed inside _dispatch_one) —
                # one ledger, not two that can drift.
                tq.batcher._dispatch_one(work)
            except BaseException as e:  # the device thread must survive
                log.error("dispatch for tenant %s failed outside the "
                          "work envelope: %s: %s", tq.name,
                          type(e).__name__, e)
            finally:
                with self._cond:
                    tq.in_flight = False
                    tq.dispatched_rows += work.n
                    tq.dispatched_bucket_rows += work.bucket
                    tq.dispatched_batches += 1
                    tq.dispatched_device_s += work.dispatch_s
                    self._cond.notify_all()

    def close(self, timeout_s: float = 60.0) -> None:
        """Stop the device thread once the queues drain.  Tenant
        batchers should already be closed (each drains + unregisters on
        its own shutdown path); any straggler work still queued is
        dispatched before the thread exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout_s)
