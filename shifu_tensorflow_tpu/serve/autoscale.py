"""SLO-driven serve autoscaling — the control loop that closes the
signals the obs plane already journals onto the actuators the supervisor
already owns (ROADMAP item 3, serve side).

Signals (all journal-borne, so a policy decision is reconstructable from
a dead fleet's files): the PR-7 watchdog's hysteretic ``slo_breach`` /
``slo_recover`` transitions on ``serve_p99_s`` / ``serve_shed_rate``
(fleet-wide and per-tenant ``:model`` variants), plus the rate-limited
``shed`` events' per-tenant monotonic counters.  Actuators (applied by
``serve/__main__._supervise``): add an SO_REUSEPORT scoring worker up to
``shifu.tpu.serve-workers-max``; SIGTERM-drain one back on sustained
recovery; and — BEFORE scaling — rebalance a single overloading tenant's
DRR weight down (``--tenant-weight`` override on a rolling restart),
because one hot tenant starving its peers is a fairness problem capacity
cannot fix.

The policy here is PURE (observations in, at most one Decision out, a
injectable clock) so the hysteresis/cooldown/ordering semantics are unit
-testable without processes; the supervisor owns all side effects.

Anti-flap discipline, layered:
- the slo_breach events feeding the loop are already hysteretic
  (obs/slo.py holds a state for ``slo-hysteresis`` evaluations);
- the policy requires ``ticks`` consecutive breached polls before acting
  and ``recovery_ticks`` consecutive CLEAN polls before shrinking;
- every decision opens a ``cooldown_s`` window during which the policy
  holds still;
- empty-window discipline (the PR-7/PR-13 lesson, adapted): a tick with
  NO new journal events is NEUTRAL while a breach is latched — a
  latched breach whose writer went quiet is a dead worker, not fresh
  overload evidence, so it must never drive a scale_up; and before the
  journal has produced ANY event the policy stays inert (nothing
  proves the fleet is even wired to it).  A quiet, UN-breached fleet
  does accrue recovery credit — traffic going away entirely is the
  purest recovery there is, and the slo watchdog already journals
  ``slo_recover`` on a drained window for exactly this reason.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.utils import logs

log = logs.get("autoscale")

#: serve signals the policy treats as overload evidence (bare and
#: per-tenant ``:model`` forms)
_BREACH_SIGNALS = ("serve_p99_s", "serve_shed_rate")


@dataclass(frozen=True)
class AutoscaleConfig:
    workers_min: int
    workers_max: int
    ticks: int = K.DEFAULT_SERVE_AUTOSCALE_TICKS
    recovery_ticks: int = K.DEFAULT_SERVE_AUTOSCALE_RECOVERY_TICKS
    cooldown_s: float = K.DEFAULT_SERVE_AUTOSCALE_COOLDOWN_S
    # one tenant owning at least this fraction of the window's NEW sheds
    # (with >1 tenant serving) reads as single-tenant overload:
    # rebalance its weight down before adding capacity
    dominance: float = 0.8
    # weight multiplier applied per rebalance, floored so a tenant can
    # be tamed but never starved into un-serveability
    rebalance_backoff: float = 0.5
    weight_floor: float = 0.25


@dataclass(frozen=True)
class Decision:
    action: str  # "scale_up" | "scale_down" | "rebalance"
    reason: str
    evidence: dict
    # rebalance only: the tenant and its NEW weight
    model: str | None = None
    weight: float | None = None


@dataclass
class TickObservation:
    """One policy poll's view of the journal (built by JournalSignals or
    a test)."""

    #: new journal events since the last poll (0 + nothing breached =
    #: neutral tick)
    new_events: int = 0
    #: serve signals currently in breach (last transition was
    #: slo_breach), e.g. {"serve_p99_s", "serve_shed_rate:alpha"}
    breached: set = field(default_factory=set)
    #: cumulative shed counts per tenant (None key = single-model); the
    #: policy diffs these between polls itself
    sheds_by_model: dict = field(default_factory=dict)
    #: distinct tenants observed serving (rebalance needs > 1)
    tenants_seen: int = 0
    #: the journal could not be read this tick: the policy must treat
    #: it as fully NEUTRAL (no breach debounce reset, no recovery
    #: credit) — an unreadable journal is evidence of nothing
    read_error: bool = False


class AutoscalePolicy:
    """Hysteretic scale/rebalance policy.  Call ``observe`` once per
    tick; it returns at most one Decision (the supervisor applies it and
    reports the applied worker count back on the next tick)."""

    def __init__(self, cfg: AutoscaleConfig, clock=time.monotonic):
        self.cfg = cfg
        self._clock = clock
        self._breach_ticks = 0
        self._clean_ticks = 0
        self._seen_any = False
        self._last_action_ts: float | None = None
        #: tenant -> current weight override (starts unset = 1.0); the
        #: supervisor reads this to build --tenant-weight args
        self.weight_overrides: dict[str, float] = {}
        # shed totals at the LAST action (dominance judges the burst
        # since then, not all history)
        self._shed_base: dict = {}

    def in_cooldown(self) -> bool:
        return (self._last_action_ts is not None
                and self._clock() - self._last_action_ts
                < self.cfg.cooldown_s)

    def cooldown_remaining_s(self) -> float:
        if self._last_action_ts is None:
            return 0.0
        return max(0.0, self.cfg.cooldown_s
                   - (self._clock() - self._last_action_ts))

    def _new_sheds(self, obs: TickObservation) -> dict:
        out = {}
        for m, total in obs.sheds_by_model.items():
            delta = int(total) - int(self._shed_base.get(m, 0))
            if delta > 0:
                out[m] = delta
        return out

    def _decide(self, decision: Decision, obs: TickObservation) -> Decision:
        self._last_action_ts = self._clock()
        self._breach_ticks = 0
        self._clean_ticks = 0
        self._shed_base = dict(obs.sheds_by_model)
        return decision

    def observe(self, obs: TickObservation,
                workers: int) -> Decision | None:
        if obs.read_error:
            # a failed journal read proves nothing: hold every counter
            # still — six blips in a row must not shrink a breached
            # fleet, and one must not reset the scale_up debounce
            return None
        breached = bool(obs.breached)
        if obs.new_events == 0:
            if breached or not self._seen_any:
                # neutral: a latched breach with no fresh events is a
                # dead writer, not overload evidence; and before any
                # event at all, nothing proves the journal is wired
                return None
            # quiet AND un-breached = recovered/idle: recovery credit
            self._breach_ticks = 0
            self._clean_ticks += 1
        elif breached:
            self._seen_any = True
            self._clean_ticks = 0
            self._breach_ticks += 1
        else:
            self._seen_any = True
            self._breach_ticks = 0
            self._clean_ticks += 1
        if self.in_cooldown():
            return None
        cfg = self.cfg
        if breached and self._breach_ticks >= cfg.ticks:
            evidence = {
                "breached": sorted(obs.breached),
                "breach_ticks": self._breach_ticks,
                "new_sheds": self._new_sheds(obs),
                "workers": workers,
            }
            hot = self._dominant_tenant(obs)
            if hot is not None:
                current = self.weight_overrides.get(hot, 1.0)
                new = max(cfg.weight_floor,
                          current * cfg.rebalance_backoff)
                if new < current or hot not in self.weight_overrides:
                    self.weight_overrides[hot] = new
                    return self._decide(Decision(
                        action="rebalance",
                        reason=(f"tenant {hot} owns the overload "
                                f"(>= {cfg.dominance:.0%} of window "
                                f"sheds): weight -> {new:g} before "
                                f"scaling"),
                        evidence=evidence, model=hot, weight=new,
                    ), obs)
                # already floored: fall through to capacity
            if workers < cfg.workers_max:
                return self._decide(Decision(
                    action="scale_up",
                    reason=(f"{sorted(obs.breached)} breached for "
                            f"{self._breach_ticks} tick(s)"),
                    evidence=evidence,
                ), obs)
            return None  # at ceiling; keep the breach counters running
        if (not breached and self._clean_ticks >= cfg.recovery_ticks
                and workers > cfg.workers_min):
            return self._decide(Decision(
                action="scale_down",
                reason=(f"recovered for {self._clean_ticks} "
                        f"clean tick(s)"),
                evidence={"clean_ticks": self._clean_ticks,
                          "workers": workers},
            ), obs)
        return None

    def _dominant_tenant(self, obs: TickObservation) -> str | None:
        """The single tenant owning the overload, if any: >1 tenants
        serving AND one named tenant holds >= ``dominance`` of the NEW
        sheds since the last action."""
        if obs.tenants_seen < 2:
            return None
        new = self._new_sheds(obs)
        named = {m: n for m, n in new.items() if m}
        total = sum(new.values())
        if not named or total <= 0:
            return None
        hot, n = max(named.items(), key=lambda kv: kv[1])
        if n / total >= self.cfg.dominance:
            return hot
        return None


class JournalSignals:
    """Incremental journal reader feeding the policy: breach state per
    signal, per-tenant shed counters, and the new-event count per poll —
    all from the serve fleet's journal base + ``.s<i>`` siblings, the
    same files ``obs summary`` reads off a dead fleet.

    State folds INCREMENTALLY: each poll processes only the events past
    the last watermark (read_events' parse cache already makes the file
    reads incremental; without this fold a long-lived fleet would pay an
    O(total-events) Python scan per tick).  A writer's latched breach is
    cleared when that writer restarts (``serve_start`` — a fresh
    process's watchdog starts un-breached) or leaves
    (``serve_worker_exit``/``scale_down``): a dead writer cannot emit
    its own ``slo_recover``, and without this the rebalance rolling
    restart would latch a breach forever and drive scale_ups to the
    ceiling."""

    def __init__(self, journal_base: str):
        from shifu_tensorflow_tpu.obs.journal import read_keyed_events

        self._read_keyed = read_keyed_events
        self.base = journal_base
        self._cache: dict = {}
        # per-WRITER-file high-water mark over the (ts, seq) merge key —
        # NOT a global list index: a slow writer's flush can merge its
        # events BEFORE an already-seen faster writer's tail, and
        # rotation dropping the oldest file can shrink the merged list;
        # a per-writer watermark survives both (ts, seq is monotonic
        # within a writer, and rotation only drops events <= the mark)
        self._marks: dict = {}      # writer-file id -> (ts, seq)
        # folded state (survives across polls)
        self._breached: dict = {}   # (worker, signal) -> bool
        self._sheds: dict = {}      # (worker, model) -> monotonic max
        # shed counts already credited to dead processes of a writer
        # index: a restarted worker's shed_total restarts near 0, and
        # max() alone would mask its fresh sheds until they beat the
        # dead process's high-water — blinding dominance detection
        self._retired_sheds: dict = {}
        self._tenants: set = set()

    def _clear_writer(self, worker) -> None:
        for key in [k for k in self._breached if k[0] == worker]:
            self._breached[key] = False
        # retire the dead process's shed high-water so the fresh
        # process's counters are visible from 0 (totals stay monotonic)
        for key in [k for k in self._sheds if k[0] == worker]:
            self._retired_sheds[key] = (
                self._retired_sheds.get(key, 0) + self._sheds.pop(key))

    def poll(self) -> TickObservation:
        try:
            # after= pushes the watermarks down into the reader: events
            # at or below a writer's mark are neither keyed nor sorted,
            # and unchanged files wholly below it are skipped outright —
            # a steady-state tick pays for the new tail only
            keyed = self._read_keyed(self.base, cache=self._cache,
                                     after=self._marks)
        except Exception:
            log.exception("autoscale journal read failed (%s)", self.base)
            return TickObservation(read_error=True)
        new = []
        marks = self._marks
        for ts, writer, seq, ev in keyed:
            if (ts, seq) <= marks.get(writer, (-1.0, -1)):
                continue
            marks[writer] = (ts, seq)
            new.append(ev)
        for ev in new:
            if ev.get("plane") != "serve":
                continue
            kind = ev.get("event")
            if kind == "slo_breach":
                sig = str(ev.get("signal") or "")
                if sig.split(":", 1)[0] in _BREACH_SIGNALS:
                    # per (writer, signal): worker 1 recovering must not
                    # mask worker 0's still-open breach of the same name
                    self._breached[(ev.get("worker"), sig)] = True
            elif kind == "slo_recover":
                self._breached[(ev.get("worker"),
                                str(ev.get("signal") or ""))] = False
            elif kind == "serve_start":
                # this writer index restarted: its previous process's
                # latched breaches died with it
                self._clear_writer(ev.get("worker"))
            elif kind in ("serve_worker_exit", "scale_down"):
                self._clear_writer(ev.get("index"))
            elif kind == "shed":
                m = ev.get("model")
                # shed_total is a per-WRITER per-model monotonic counter:
                # take each writer's max, sum across writers below
                key = (ev.get("worker"), m)
                self._sheds[key] = max(
                    int(self._sheds.get(key, 0)),
                    int(ev.get("shed_total", 0) or 0))
                if m:
                    self._tenants.add(m)
            elif kind == "serve_batch":
                m = ev.get("model")
                if m:
                    self._tenants.add(m)
        by_model: dict = {}
        for src in (self._sheds, self._retired_sheds):
            for (w, m), n in src.items():
                by_model[m] = by_model.get(m, 0) + n
        return TickObservation(
            new_events=len(new),
            breached={sig for (_, sig), b in self._breached.items()
                      if b},
            sheds_by_model=by_model,
            tenants_seen=len(self._tenants),
        )
