"""Staged pull-based ingest pipeline — the tf.data-class rebuild of the
streaming input path (ROADMAP item 2; design per the tf.data paper,
PAPERS.md: "tf.data: A Machine Learning Data Processing Framework").

The previous ``ShardStream`` was one producer thread per file bucket doing
read → inflate → parse → finalize → batch serially, so the ``n_readers``
knob bought nothing (BENCH_INGEST_HOST cold scaling 1.0×/0.99×/1.02×) and
reader count changed the batch order (parallel ingest was opt-in and
irreproducible).  This module splits the work into composable stages with
bounded queues, each timed under its own obs span:

    shard plan ──► reader threads ──► decode pool ──► sequencer ──► shuffle
    (static          (IO + fused        (parse /        (ordered      buffer
     round-robin)     native stream)     finalize /      merge,        (seeded)
                                         cast)           pull-based)

- **readers** (``ingest.read``): N threads; shard *i* belongs to reader
  ``i % N`` — a static, deterministic assignment.  Each reader walks its
  shards in ascending order, producing chunk payloads (cache blocks /
  native-parsed arrays / raw byte chunks) into its own bounded queue.
  Per-shard transient faults retry under the PR-1 envelope
  (utils/retry.call, site ``ingest.read``) with chunk-offset resume:
  chunking is deterministic, so a re-opened shard skips the chunks it
  already submitted and continues where the fault hit — same contract the
  fs layer's ResumableReader gives remote byte streams, one level up.
  Chaos seam: ``faults.check("ingest.read.s<shard>")`` before every chunk.
- **decode pool** (``ingest.decode``): a shared thread pool running
  parse + finalize(ZSCALE/weight-clamp) + transport-dtype cast.  Readers
  submit each chunk and enqueue the *future*, so decode parallelism never
  reorders anything.  Cache-hit blocks (already finalized, memmap'd)
  bypass the pool entirely — the warm path stays zero-copy.
- **sequencer** (``ingest.wait``): the pull stage.  Runs in the consumer's
  thread, draining reader queues in global shard order and resolving
  futures in submission order — so the emitted block stream is a pure
  function of (path list, schema, salt), independent of reader count,
  decode width, queue depths, and thread interleaving.  ``ingest.wait``
  is the consumer-visible stall: the time the training loop actually
  waited on ingest.
- **shuffle** (``ingest.shuffle``): optional seeded window shuffle
  (``shuffle_rows`` > 0): consecutive windows of that many rows are
  permuted with a ``numpy`` Generator seeded from (seed, epoch-salt) —
  deterministic for a fixed seed regardless of parallelism.

Batching (fixed shapes, zero-weight padding) happens after the shuffle in
the same pull path, so batch composition at shard boundaries is identical
across reader counts — the property the seeded-shuffle reproducibility
tests pin (tests/test_ingest.py).

Lifecycle: the pipeline owns threads, so abandoning an epoch mid-stream
must release them.  ``close()`` (idempotent, also wired into the
generator's ``finally`` and ``ShardStream.close()``) stops producers,
drains queues so no thread is wedged on a full queue, joins readers,
shuts the decode pool down, and aborts any uncommitted cache writers.
Every trainer epoch path closes its stream in a ``finally``
(train/trainer.py), so health-guard rollbacks and mid-epoch exceptions
cannot leak producer threads.

Autotuning: ``StageStats`` accumulates per-stage busy/wait seconds; the
``data/autotune.py`` policy reads them (plus the installed tracer's step
spans) to size readers / decode workers / prefetch between epochs.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Iterator, Sequence

import numpy as np

from shifu_tensorflow_tpu.data.reader import (
    ParsedBlock,
    RecordSchema,
    route_is_valid,
    wanted_columns,
)
from shifu_tensorflow_tpu.utils import faults
from shifu_tensorflow_tpu.utils import fs
from shifu_tensorflow_tpu.utils import logs
from shifu_tensorflow_tpu.utils import retry as retry_util

log = logs.get("ingest")

_perf = time.perf_counter

#: queue markers (tuples keyed by these sentinels)
_SHARD_START = object()
_SHARD_END = object()


class StreamClosed(RuntimeError):
    """Raised by the sequencer when the pipeline is closed underneath it
    (another thread called close() while this one was pulling)."""


@dataclass
class StageStats:
    """Per-epoch stage accounting the autotuner consumes.  All the ``*_s``
    fields are SUMS across threads (2 readers busy for 1s each = 2.0), so
    busy fractions divide by the stage width × wall."""

    readers: int = 0
    decode_workers: int = 0
    read_s: float = 0.0  # reader-thread time producing chunks
    decode_s: float = 0.0  # decode-pool time parsing/finalizing
    wait_s: float = 0.0  # consumer-visible stall pulling the next block
    shuffle_s: float = 0.0
    rows: int = 0
    chunks: int = 0
    cache_chunks: int = 0  # chunks served from the binary shard cache
    retries: int = 0  # shard read attempts that were retried
    wall_s: float = 0.0  # first-pull → close wall clock
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + seconds)

    def bump(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def busy_fractions(self) -> dict[str, float]:
        """Stage busy / starvation ratios, each in [0, ~1]."""
        wall = self.wall_s or 1e-9
        return {
            "read_busy": self.read_s / (max(1, self.readers) * wall),
            "decode_busy": self.decode_s / (max(1, self.decode_workers)
                                            * wall),
            "wait_frac": min(1.0, self.wait_s / wall),
        }

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return {
                "readers": self.readers,
                "decode_workers": self.decode_workers,
                "read_s": round(self.read_s, 6),
                "decode_s": round(self.decode_s, 6),
                "wait_s": round(self.wait_s, 6),
                "shuffle_s": round(self.shuffle_s, 6),
                "rows": self.rows,
                "chunks": self.chunks,
                "cache_chunks": self.cache_chunks,
                "retries": self.retries,
                "wall_s": round(self.wall_s, 6),
            }


class _Ready:
    """A pre-resolved 'future' for payloads that need no decode work
    (cache-hit blocks) — keeps the warm memmap path off the pool."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


class _Err:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class ShardPipeline:
    """Parallel shard readers + decode pool + ordered pull sequencer.

    ``blocks()`` yields ``(full_block, hashes)`` tuples in deterministic
    (shard, chunk) order; routing/shuffle/batching stay with the caller
    (data/dataset.ShardStream).  The caller owns the lifecycle: iterate
    ``blocks()`` to completion or call ``close()``.
    """

    def __init__(
        self,
        paths: Sequence[str],
        schema: RecordSchema,
        *,
        salt: int = 0,
        n_readers: int = 1,
        decode_workers: int = 1,
        queue_depth: int = 4,
        block_bytes: int = 4 << 20,
        block_rows: int = 1 << 16,
        cache_dir: str | None = None,
        feature_dtype: str = "float32",
        need_hashes: bool = False,
        retry_policy: "retry_util.RetryPolicy | None" = None,
        stats: StageStats | None = None,
        close_timeout_s: float = 10.0,
        tracer=None,
        fault_site_prefix: str = "ingest",
        shard_offset: int = 0,
    ):
        self.paths = list(paths)
        self.schema = schema
        self.salt = salt
        self.n_readers = max(1, min(int(n_readers), max(1, len(self.paths))))
        self.decode_workers = max(1, int(decode_workers))
        self.queue_depth = max(1, int(queue_depth))
        self.block_bytes = block_bytes
        self.block_rows = max(1, int(block_rows))
        self.cache_dir = cache_dir
        self.feature_dtype = feature_dtype or "float32"
        self.need_hashes = need_hashes
        self.retry_policy = retry_policy
        self.stats = stats if stats is not None else StageStats()
        self.stats.readers = self.n_readers
        self.stats.decode_workers = self.decode_workers

        self.close_timeout_s = close_timeout_s
        # chaos-seam identity: the bulk scorer runs one pipeline PER
        # LEASED SHARD and keys faults to the job-global shard id
        # ("score.read.s<shard>"), not this pipeline's local index —
        # prefix + offset let it do that without a parallel seam scheme
        self.fault_site_prefix = fault_site_prefix
        self.shard_offset = int(shard_offset)
        # EXPLICIT span sink only (no fallback to the process-global
        # install): the validation stream runs untraced on purpose —
        # its ingest work must not inflate the train epoch's journaled
        # span budget (same discipline as _PipelinedPrefetch's
        # step.infeed.* seams, data/dataset.py)
        self.tracer = tracer
        self._stop = threading.Event()
        self._close_lock = threading.Lock()
        self._queues: list[queue.Queue] = [
            queue.Queue(maxsize=self.queue_depth)
            for _ in range(self.n_readers)
        ]
        self._pool: ThreadPoolExecutor | None = None
        self._threads: list[threading.Thread] = []
        self._writers: list = []  # live (uncommitted) cache writers
        self._writers_lock = threading.Lock()
        self._started = False
        self._closed = False
        self._t_start = 0.0

    # ---- lifecycle --------------------------------------------------------

    def start(self) -> "ShardPipeline":
        if self._started:
            return self
        self._started = True
        self._t_start = _perf()
        self._pool = ThreadPoolExecutor(
            max_workers=self.decode_workers,
            thread_name_prefix="stpu-ingest-decode",
        )
        self._threads = [
            threading.Thread(
                target=self._reader_main, args=(r,),
                name=f"stpu-ingest-read-{r}", daemon=True,
            )
            for r in range(self.n_readers)
        ]
        for t in self._threads:
            t.start()
        return self

    def close(self) -> None:
        """Stop producers, join threads, release the pool, abort
        uncommitted cache writers.  Idempotent; safe from any thread
        (the generator's ``finally`` and an abandoning consumer may race
        here — the lock serializes them, and the loser sees ``_closed``).

        The join is BOUNDED (``close_timeout_s``): a reader wedged in an
        uninterruptible fs read (dead remote socket with no timeout) can
        never observe the stop event, and an unbounded join would turn a
        health-guard rollback into an indefinite hang — worse than the
        thread leak it prevents.  Past the deadline the daemon thread is
        abandoned with a warning; it exits on its own the moment the
        blocked read returns (every loop edge checks ``_stop``)."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
            self._stop.set()
            deadline = _perf() + self.close_timeout_s
            # drain so a producer blocked on a full queue can observe stop
            for t in self._threads:
                while t.is_alive():
                    for q in self._queues:
                        try:
                            q.get_nowait()
                        except queue.Empty:
                            pass
                    t.join(timeout=0.05)
                    if t.is_alive() and _perf() > deadline:
                        log.warning(
                            "ingest reader %s did not exit within %.1fs of "
                            "close() (blocked in an uninterruptible read?) "
                            "— abandoning the daemon thread", t.name,
                            self.close_timeout_s)
                        break
            self._finish_close()

    def _finish_close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None
        with self._writers_lock:
            writers, self._writers = self._writers[:], []
        for w in writers:
            try:
                w.abort()
            except Exception:
                pass
        if self._t_start:
            self.stats.wall_s = _perf() - self._t_start

    # ---- reader stage -----------------------------------------------------

    def _put(self, q: "queue.Queue", item) -> bool:
        """Bounded put that gives up when the pipeline is closing — a plain
        q.put could wedge a thread forever on an abandoned iterator."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _reader_main(self, r: int) -> None:
        q = self._queues[r]
        try:
            for shard_idx in range(r, len(self.paths), self.n_readers):
                self._produce_shard(shard_idx, self.paths[shard_idx], q)
                if self._stop.is_set():
                    return
        except BaseException as e:  # surface to the consumer, never die mute
            self._put(q, _Err(e))

    def _produce_shard(self, shard_idx: int, path: str,
                       q: "queue.Queue") -> None:
        """Emit one shard: START(writer) marker, chunk futures, END marker.
        Transient read faults retry with chunk-offset resume."""
        from shifu_tensorflow_tpu.data import cache as shard_cache

        cache_reader = None
        writer = None
        if self.cache_dir is not None:
            cache_reader = shard_cache.lookup(
                self.cache_dir, path, self.schema, self.salt,
                self.feature_dtype,
            )
            if cache_reader is not None and (
                    self.need_hashes and not cache_reader.has_hashes):
                cache_reader = None  # entry unusable for routed streams
            if cache_reader is None:
                writer = shard_cache.ShardCacheWriter(
                    self.cache_dir, path, self.schema, self.salt,
                    self.feature_dtype,
                )
                with self._writers_lock:
                    self._writers.append(writer)
        want_hashes = self.need_hashes or writer is not None

        if not self._put(q, (_SHARD_START, shard_idx, writer)):
            return
        submitted = 0

        def attempt() -> None:
            nonlocal submitted
            emitted = 0
            site = (f"{self.fault_site_prefix}.read."
                    f"s{self.shard_offset + shard_idx}")
            t0 = _perf()
            for payload in self._shard_chunks(path, cache_reader,
                                              want_hashes):
                if self._stop.is_set():
                    return
                if emitted < submitted:
                    # resume: chunking is deterministic, so everything
                    # submitted before the fault is simply skipped
                    emitted += 1
                    continue
                faults.check(site)
                dt = _perf() - t0
                self.stats.add("read_s", dt)
                if self.tracer is not None:
                    self.tracer.add("ingest.read", dt)
                fut = self._submit_decode(payload)
                if not self._put(q, (shard_idx, fut)):
                    return
                emitted += 1
                submitted = emitted
                self.stats.bump("chunks")
                if payload[0] == "block":  # memmap'd cache hit
                    self.stats.bump("cache_chunks")
                t0 = _perf()

        def on_retry_classify(exc: BaseException) -> bool:
            ok = retry_util.retryable(exc)
            if ok:
                self.stats.bump("retries")
            return ok

        retry_util.call(attempt, policy=self.retry_policy,
                        site=f"{self.fault_site_prefix}.read",
                        classify=on_retry_classify)
        self._put(q, (_SHARD_END, shard_idx, None))

    def _shard_chunks(self, path, cache_reader, want_hashes):
        """Deterministic chunk payloads for one shard, tagged by decode
        mode.  Chunk boundaries are a pure function of the source + fixed
        block sizes, so a retried shard re-produces the identical
        sequence (the resume skip depends on it)."""
        from shifu_tensorflow_tpu.data import native

        if cache_reader is not None:
            for block, hashes in cache_reader.blocks():
                yield ("block", block, hashes)
            return

        if "://" not in path or path.startswith("file://"):
            gen = native.stream_blocks(
                fs.strip_local(path), wanted_columns(self.schema),
                self.schema.delimiter, salt=self.salt,
                want_hashes=want_hashes, block_rows=self.block_rows,
            )
            if gen is not None:
                for arr, hashes in gen:
                    yield ("raw", arr, hashes)
                return

        yield from self._byte_chunks(path, want_hashes)

    def _byte_chunks(self, path: str, want_hashes: bool):
        """fs-layer fallback: decompressed byte chunks cut at line
        boundaries; the parse itself happens in the decode pool."""
        tail = b""
        with fs.open_maybe_gzip(path) as f:
            while True:
                chunk = f.read(self.block_bytes)
                if not chunk:
                    break
                data = tail + chunk
                cut = data.rfind(b"\n")
                if cut < 0:
                    tail = data
                    continue
                tail = data[cut + 1:]
                yield ("bytes", data[: cut + 1], want_hashes)
        if tail:
            yield ("bytes", tail, want_hashes)

    # ---- decode stage -----------------------------------------------------

    def _submit_decode(self, payload):
        kind = payload[0]
        if kind == "block":  # cache hit: already finalized, zero-copy
            return _Ready((payload[1], payload[2]))
        return self._pool.submit(self._decode, payload)

    def _decode(self, payload):
        """Pool worker: parse/finalize/cast one chunk → (block, hashes).
        The heavy pieces (native parse, numpy copies, zlib) release the
        GIL, so pool width scales real work on multi-core hosts; the
        pure-Python parse fallback is GIL-bound and only overlaps IO."""
        from shifu_tensorflow_tpu.data.reader import _finalize

        t0 = _perf()
        try:
            kind = payload[0]
            if kind == "raw":
                _, arr, hashes = payload
            else:  # "bytes"
                _, buf, want_hashes = payload
                arr, hashes = self._parse_bytes(buf, want_hashes)
            block = self._cast(_finalize(arr, self.schema))
            return block, hashes
        finally:
            dt = _perf() - t0
            self.stats.add("decode_s", dt)
            if self.tracer is not None:
                self.tracer.add("ingest.decode", dt)

    def _parse_bytes(self, buf: bytes, want_hashes: bool):
        from shifu_tensorflow_tpu.data import native
        from shifu_tensorflow_tpu.data.reader import parse_lines_full

        parsed = native.parse_buffer(
            buf, wanted_columns(self.schema), self.schema.delimiter,
            salt=self.salt, want_hashes=want_hashes,
        )
        if parsed is None:
            parsed = parse_lines_full(buf, self.schema, self.salt,
                                      want_hashes)
        return parsed

    def _cast(self, block: ParsedBlock) -> ParsedBlock:
        if self.feature_dtype == "float32":
            return block
        from shifu_tensorflow_tpu.data.cache import feature_np_dtype

        return ParsedBlock(
            block.features.astype(feature_np_dtype(self.feature_dtype)),
            block.targets, block.weights,
        )

    # ---- sequencer (pull stage) -------------------------------------------

    def _get(self, q: "queue.Queue"):
        t0 = _perf()
        try:
            while True:
                if self._stop.is_set():
                    raise StreamClosed("ingest pipeline closed mid-pull")
                try:
                    return q.get(timeout=0.1)
                except queue.Empty:
                    continue
        finally:
            dt = _perf() - t0
            self.stats.add("wait_s", dt)
            if self.tracer is not None:
                self.tracer.add("ingest.wait", dt)

    def blocks(self) -> Iterator[tuple[ParsedBlock, "np.ndarray | None"]]:
        """Yield (full finalized block, routing hashes) in deterministic
        shard→chunk order.  Cache writers are fed and committed here —
        the sequencer is the only stage that sees decoded blocks in
        order."""
        self.start()
        try:
            for shard_idx in range(len(self.paths)):
                q = self._queues[shard_idx % self.n_readers]
                writer = None
                started = False
                while True:
                    item = self._get(q)
                    if isinstance(item, _Err):
                        raise item.exc
                    tag = item[0]
                    if tag is _SHARD_START:
                        assert item[1] == shard_idx, "sequencer desync"
                        writer = item[2]
                        started = True
                        continue
                    if tag is _SHARD_END:
                        assert item[1] == shard_idx, "sequencer desync"
                        if writer is not None:
                            writer.commit()
                            with self._writers_lock:
                                if writer in self._writers:
                                    self._writers.remove(writer)
                        break
                    assert started and item[0] == shard_idx
                    block, hashes = self._resolve(item[1])
                    if writer is not None:
                        writer.append(block, hashes)
                    self.stats.bump("rows", len(block))
                    yield block, hashes
        finally:
            self.close()

    def _resolve(self, fut):
        if isinstance(fut, _Ready):
            return fut.result()
        t0 = _perf()
        try:
            return fut.result()
        finally:
            dt = _perf() - t0
            self.stats.add("wait_s", dt)
            if self.tracer is not None:
                self.tracer.add("ingest.wait", dt)


# ---- shuffle stage ---------------------------------------------------------

def shuffled_blocks(
    blocks: Iterator[ParsedBlock],
    shuffle_rows: int,
    seed: int,
    stats: StageStats | None = None,
    tracer=None,
) -> Iterator[ParsedBlock]:
    """Seeded window shuffle: buffer ``shuffle_rows`` rows, permute the
    window, emit it as one block.  Output is a pure function of (input
    order, shuffle_rows, seed) — and input order is deterministic
    (sequencer contract) — so a fixed seed reproduces the epoch order
    bit-identically at any reader/decode width."""
    if shuffle_rows <= 0:
        yield from blocks
        return
    rng = np.random.default_rng(seed)
    buf: list[ParsedBlock] = []
    buffered = 0

    def _flush() -> ParsedBlock:
        nonlocal buf, buffered
        t0 = _perf()
        merged = buf[0] if len(buf) == 1 else ParsedBlock.concat(buf)
        perm = rng.permutation(len(merged))
        out = ParsedBlock(
            merged.features[perm], merged.targets[perm],
            merged.weights[perm],
        )
        buf, buffered = [], 0
        if stats is not None:
            stats.add("shuffle_s", _perf() - t0)
        if tracer is not None:
            tracer.add("ingest.shuffle", _perf() - t0)
        return out

    for b in blocks:
        if len(b) == 0:
            continue
        buf.append(b)
        buffered += len(b)
        if buffered >= shuffle_rows:
            yield _flush()
    if buf:
        yield _flush()


# ---- routing + batch formation (pull path) ---------------------------------

def route_blocks(
    blocks: Iterator[tuple[ParsedBlock, "np.ndarray | None"]],
    *,
    emit: str,
    valid_rate: float,
) -> Iterator[ParsedBlock]:
    """Select the train/valid side of each block by the deterministic
    per-row content hash (reader.route_is_valid)."""
    for block, hashes in blocks:
        if valid_rate <= 0.0:
            if emit == "train" and len(block):
                yield block
            continue
        if hashes is None:
            raise ValueError("valid_rate > 0 requires routing hashes")
        is_valid = route_is_valid(hashes, valid_rate)
        keep = is_valid if emit == "valid" else ~is_valid
        if keep.all():
            if len(block):
                yield block
            continue
        kept = ParsedBlock(
            block.features[keep], block.targets[keep], block.weights[keep]
        )
        if len(kept):
            yield kept


def blocks_to_batches(
    blocks: Iterator[ParsedBlock],
    batch_size: int,
    num_features: int,
    *,
    drop_remainder: bool = False,
    stats_tap=None,
) -> Iterator[dict]:
    """Fixed-size batch formation with a single global carry.  Full
    batches inside a block are pure slices (views — zero copy on the
    memmap'd cache path); only carry top-ups at block boundaries copy.
    Because the pipeline is order-preserving, there is exactly ONE tail
    (at most batch_size-1 rows) regardless of reader count.

    ``stats_tap`` is the data-observability feed (an object with
    ``add_block(features)`` — obs/datastats.TrainDataSketch): each
    PRE-batching block's feature matrix is offered before slicing, so
    the sketch never sees the zero-weight padding rows the tail batch
    gains below.  Explicit-sink discipline, like the pipeline tracer:
    the caller decides which streams feed it (train-emit only — a
    validation stream polluting the exported baseline would hide
    exactly the train/serve skew the sketch exists to catch)."""
    from shifu_tensorflow_tpu.data.dataset import make_batch, pad_to_batch

    B = batch_size
    carry: ParsedBlock | None = None
    for block in blocks:
        if stats_tap is not None and len(block):
            stats_tap.add_block(block.features)
        i = 0
        if carry is not None and len(carry):
            take = min(B - len(carry), len(block))
            if take:
                carry = ParsedBlock.concat([
                    carry,
                    ParsedBlock(block.features[:take], block.targets[:take],
                                block.weights[:take]),
                ])
                i = take
            if len(carry) < B:
                continue
            yield make_batch(carry.features, carry.targets, carry.weights)
            carry = None
        n_full = i + ((len(block) - i) // B) * B
        for j in range(i, n_full, B):
            sl = slice(j, j + B)
            yield make_batch(block.features[sl], block.targets[sl],
                             block.weights[sl])
        if n_full < len(block):
            carry = ParsedBlock(
                block.features[n_full:], block.targets[n_full:],
                block.weights[n_full:],
            )
        else:
            carry = None
    if carry is not None and len(carry) and not drop_remainder:
        padded = pad_to_batch(carry, B)
        for i in range(0, len(padded), B):
            sl = slice(i, i + B)
            yield make_batch(padded.features[sl], padded.targets[sl],
                             padded.weights[sl])


# ---- knob resolution -------------------------------------------------------

@dataclass(frozen=True)
class IngestKnobs:
    """Resolved stage widths for one stream build."""

    readers: int = 1
    decode_workers: int = 1
    prefetch: int = 2  # device-put pipeline depth (batches in flight)


def default_knobs(cpu_count: int | None = None) -> IngestKnobs:
    """Conservative starting point the autotuner grows from: one reader
    per core up to 2, one decode worker, prefetch 2."""
    cpus = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
    return IngestKnobs(readers=min(2, max(1, cpus)), decode_workers=1,
                       prefetch=2)
