"""Dataset utility CLI: materialize the binary shard cache ahead of
training (and inspect/prune it).

The reference had no equivalent — every run re-parsed gzip PSV from
scratch (ssgd_monitor.py:348-454).  Pre-building the cache moves the
one-time parse cost out of the training job entirely, so even the first
epoch streams memory-mapped tensors:

    python -m shifu_tensorflow_tpu.data build \\
        --training-data-path hdfs://nn:9870/data/train \\
        --cache-dir /fast/cache --feature-columns 1,2,3 --target-column 0

    python -m shifu_tensorflow_tpu.data status --cache-dir /fast/cache
    python -m shifu_tensorflow_tpu.data prune  --cache-dir /fast/cache \\
        --max-bytes 50g
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="python -m shifu_tensorflow_tpu.data")
    sub = p.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("build", help="parse shards into the binary cache")
    b.add_argument("--training-data-path", required=True)
    b.add_argument("--cache-dir", required=True)
    b.add_argument("--feature-columns", default=None,
                   help="comma-separated column indices (or --column-config)")
    b.add_argument("--column-config", default=None,
                   help="ColumnConfig.json: column selection + ZSCALE stats")
    b.add_argument("--zscale", action="store_true",
                   help="apply ZSCALE from --column-config — MUST match the "
                        "training run's --zscale or the cache keys differ "
                        "and every lookup misses")
    b.add_argument("--target-column", type=int, default=None)
    b.add_argument("--weight-column", type=int, default=None)
    b.add_argument("--delimiter", default="|")
    b.add_argument("--salt", type=int, default=0,
                   help="MUST equal the training run's --seed (the salt is "
                        "part of the cache key and the train/valid routing)")
    b.add_argument("--feature-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="MUST match the training run's dtype gate "
                        "(bfloat16 runs on hash-free models)")
    b.add_argument("--readers", type=int, default=1,
                   help="parallel file builders (threads); cache writes "
                        "per file are independent")

    s = sub.add_parser("status", help="cache size and entry count")
    s.add_argument("--cache-dir", required=True)

    r = sub.add_parser("prune", help="evict oldest entries to a byte budget")
    r.add_argument("--cache-dir", required=True)
    r.add_argument("--max-bytes", required=True,
                   help="budget: bytes or memory string (50g, 512m)")
    return p


def _build_schema(args):
    """Mirror the training CLI's schema resolution (train/__main__.py
    resolve_schema) so the cache keys line up: same columns, same ZSCALE
    stats, same delimiter — or every training lookup would silently miss."""
    from shifu_tensorflow_tpu.config.model_config import ColumnConfig
    from shifu_tensorflow_tpu.data.reader import RecordSchema

    cc = ColumnConfig.load(args.column_config) if args.column_config else None
    if args.feature_columns:
        features = tuple(int(c) for c in args.feature_columns.split(","))
    elif cc is not None:
        features = tuple(cc.selected_column_nums)
    else:
        raise SystemExit(
            "need --feature-columns or --column-config to define the schema"
        )
    target = (args.target_column if args.target_column is not None
              else (cc.target_column_num if cc else 0))
    weight = (args.weight_column if args.weight_column is not None
              else (cc.weight_column_num if cc else -1))
    schema = RecordSchema(
        feature_columns=features, target_column=target,
        weight_column=weight, delimiter=args.delimiter,
    )
    if args.zscale:
        if cc is None:
            raise SystemExit("--zscale needs --column-config for the stats")
        means, stds = cc.zscale_stats(features)
        schema = schema.with_zscale(means, stds)
    return schema


def _build(args) -> int:
    from concurrent.futures import ThreadPoolExecutor

    from shifu_tensorflow_tpu.data import cache as shard_cache
    from shifu_tensorflow_tpu.data.dataset import ShardStream
    from shifu_tensorflow_tpu.data.splitter import list_data_files

    schema = _build_schema(args)
    paths = list_data_files(args.training_data_path)
    if not paths:
        print(f"no files under {args.training_data_path}", file=sys.stderr)
        return 2

    def build_one(path: str) -> int | None:
        # cache writes always include routing hashes, so any later
        # train/valid split serves from these entries; drain the stream
        # (drop_remainder avoids fabricating padded batches) and report
        # the COMMITTED row count from the entry itself
        stream = ShardStream(
            [path], schema, 1 << 16, valid_rate=0.0, emit="train",
            salt=args.salt, cache_dir=args.cache_dir,
            feature_dtype=args.feature_dtype, drop_remainder=True,
        )
        for _ in stream:
            pass
        reader = shard_cache.lookup(
            args.cache_dir, path, schema, args.salt, args.feature_dtype
        )
        return None if reader is None else reader.n_rows

    t0 = time.perf_counter()
    rows = 0
    cached_files = 0
    with ThreadPoolExecutor(max_workers=max(1, args.readers)) as pool:
        for i, (path, n) in enumerate(zip(paths, pool.map(build_one, paths))):
            if n is None:
                print(f"warning: {path} did not cache (source not "
                      f"fingerprintable?)", file=sys.stderr)
                continue
            cached_files += 1
            rows += n
            print(f"[{i + 1}/{len(paths)}] {path}: {n} rows", flush=True)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "files": len(paths), "cached_files": cached_files, "rows": rows,
        "rows_per_sec": round(rows / dt, 1),
        "elapsed_s": round(dt, 1),
        "cache_dir": args.cache_dir,
        "feature_dtype": args.feature_dtype,
    }), flush=True)
    # automation gates on this: a pre-warm that cached nothing (or only
    # part of the dataset) must not read as success
    return 0 if cached_files == len(paths) else 1


def _status(args) -> int:
    import os

    from shifu_tensorflow_tpu.data import cache as shard_cache

    try:
        names = os.listdir(args.cache_dir)
    except OSError as e:
        print(f"cannot read {args.cache_dir}: {e}", file=sys.stderr)
        return 2
    print(json.dumps({
        "entries": sum(1 for n in names if n.endswith(".meta.json")),
        "bytes": shard_cache.cache_size_bytes(args.cache_dir),
        "tmp_files": sum(1 for n in names if ".tmp." in n),
    }))
    return 0


def _prune(args) -> int:
    from shifu_tensorflow_tpu.config.conf import parse_memory_string
    from shifu_tensorflow_tpu.data import cache as shard_cache

    removed = shard_cache.prune_cache(
        args.cache_dir, parse_memory_string(args.max_bytes)
    )
    print(json.dumps({"removed": removed}))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "build":
        # the only subcommand that can touch jax (dataset internals);
        # status/prune stay jax-free and fast
        from shifu_tensorflow_tpu.utils.jaxenv import honor_cpu_pin

        honor_cpu_pin()
    return {"build": _build, "status": _status, "prune": _prune}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
