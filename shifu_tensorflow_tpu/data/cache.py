"""Binary columnar shard cache: parse PSV once, stream epochs at memcpy speed.

The reference re-reads and re-parses every gzip PSV shard from scratch each
run, and holds it all in Python lists (ssgd_monitor.py:348-454).  Multi-epoch
training — the normal case; the reference's own default config trains many
epochs over the same shards — re-pays the decompress+parse tax every epoch.

On the single-core TPU bench host that tax is the entire ingest budget:
measured ~206 MB/s gzip inflate + ~350 MB/s parse caps text ingest at
~0.5M rows/s, while page-cache reads run at ~1.8 GB/s (scripts/
profile_ingest.py).  So the first pass over a shard writes its *finalized*
tensors (ZSCALE applied, weights clamped — reader._finalize output) plus the
per-row train/valid routing hashes to flat binary slabs; every later epoch
memory-maps the slabs and serves batches as zero-copy views.  The file
format is deliberately dumb — one raw little-endian array per file — so a
reader is ``np.memmap`` and nothing else.

Layout, per source shard, under ``cache_dir``::

    <key>.meta.json   {"version", "n_rows", "n_features", "has_hashes",
                       "feature_dtype", ...}
    <key>.x.f32       features  (n_rows x n_features) float32, row-major
                      (or <key>.x.bf16 — bfloat16 features halve slab reads
                      and host->device bytes for bf16 training runs)
    <key>.y.f32       targets   (n_rows,) float32
    <key>.w.f32       weights   (n_rows,) float32
    <key>.h.u32       crc32 routing hashes (n_rows,) uint32   [optional]

``key`` fingerprints the source file (path, size, mtime) AND the parse
configuration (wanted columns, delimiter, ZSCALE stats, salt, format
version): any change to either produces a different key, so stale entries
are simply never looked up.  Writes go to PID-suffixed temp files renamed
into place, meta last — a cache entry either exists completely or not at
all, and concurrent builders race benignly.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
from dataclasses import dataclass
from typing import Iterator

import numpy as np

from shifu_tensorflow_tpu.data.reader import ParsedBlock, RecordSchema, wanted_columns
from shifu_tensorflow_tpu.utils import fs

CACHE_VERSION = 2
#: every slab name that can belong to an entry (both feature variants)
_SLABS = ("x.f32", "x.bf16", "y.f32", "w.f32", "h.u32")


def _feature_slab(feature_dtype: str) -> str:
    return "x.bf16" if feature_dtype == "bfloat16" else "x.f32"


def feature_np_dtype(name: str):
    """Feature-slab dtype: float32 (default) or bfloat16 — the MXU-native
    dtype, halving slab reads and host->device transfer for bf16 runs.
    Targets/weights stay float32 (loss normalization precision)."""
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if name in ("float32", "", None):
        return np.dtype(np.float32)
    raise ValueError(f"unsupported cache feature dtype {name!r}")


_feature_dtype = feature_np_dtype  # intra-module alias
# distinguishes concurrent writers for the same key within one process
# (e.g. a train and a valid ShardStream iterating at once) — PID alone
# would have them truncate each other's temp slabs
_WRITER_SEQ = itertools.count()


def cache_key(src_path: str, schema: RecordSchema, salt: int,
              feature_dtype: str = "float32") -> str | None:
    """Fingerprint of (source file identity, parse config).  None when the
    source can't be fingerprinted — size alone is NOT enough (a shard
    replaced with same-size different content would silently serve stale
    rows forever), so a modification time is required too; remote backends
    supply it via FileSystem.mtime_ns."""
    try:
        size = fs.size(src_path)
        mtime_ns = fs.mtime_ns(src_path)
    except Exception:
        return None
    if mtime_ns is None:
        return None
    ident: dict = {"path": os.path.abspath(src_path) if "://" not in src_path
                   else src_path, "size": size, "mtime_ns": mtime_ns}
    cfg = {
        "version": CACHE_VERSION,
        "feature_dtype": feature_dtype or "float32",
        "wanted": list(wanted_columns(schema)),
        "delimiter": schema.delimiter,
        "means": list(schema.means),
        "stds": list(schema.stds),
        "weight_column": schema.weight_column,
        "salt": salt,
    }
    blob = json.dumps({"src": ident, "cfg": cfg}, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()


@dataclass
class ShardCacheReader:
    """Memory-mapped view of one cached shard."""

    n_rows: int
    n_features: int
    has_hashes: bool
    features: np.ndarray  # memmap (n_rows, n_features) float32
    targets: np.ndarray  # memmap (n_rows, 1) float32
    weights: np.ndarray  # memmap (n_rows, 1) float32
    hashes: np.ndarray | None  # memmap (n_rows,) uint32

    def blocks(
        self, rows_per_block: int = 1 << 18
    ) -> Iterator[tuple[ParsedBlock, np.ndarray | None]]:
        """Yield (finalized block, hashes) as zero-copy memmap views."""
        for i in range(0, self.n_rows, rows_per_block):
            j = min(i + rows_per_block, self.n_rows)
            yield (
                ParsedBlock(self.features[i:j], self.targets[i:j], self.weights[i:j]),
                self.hashes[i:j] if self.hashes is not None else None,
            )


def lookup(cache_dir: str, src_path: str, schema: RecordSchema,
           salt: int, feature_dtype: str = "float32"
           ) -> ShardCacheReader | None:
    """Open the cache entry for ``src_path``, or None on miss/corruption."""
    key = cache_key(src_path, schema, salt, feature_dtype)
    if key is None:
        return None
    meta_path = os.path.join(cache_dir, f"{key}.meta.json")
    try:
        with open(meta_path, "r", encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if meta.get("version") != CACHE_VERSION:
        return None
    n = int(meta["n_rows"])
    nf = int(meta["n_features"])
    has_hashes = bool(meta.get("has_hashes"))
    if nf != schema.num_features:
        return None
    try:
        def mm(slab: str, dtype, shape):
            p = os.path.join(cache_dir, f"{key}.{slab}")
            expect = int(np.prod(shape)) * np.dtype(dtype).itemsize
            if os.path.getsize(p) != expect:
                raise OSError(f"cache slab size mismatch: {p}")
            if expect == 0:  # np.memmap rejects zero-length maps
                return np.empty(shape, dtype)
            return np.memmap(p, dtype=dtype, mode="r", shape=shape)

        if meta.get("feature_dtype", "float32") != (
                feature_dtype or "float32"):
            return None  # key collision should make this unreachable
        return ShardCacheReader(
            n_rows=n,
            n_features=nf,
            has_hashes=has_hashes,
            features=mm(_feature_slab(feature_dtype),
                        _feature_dtype(feature_dtype), (n, nf)),
            targets=mm("y.f32", np.float32, (n, 1)),
            weights=mm("w.f32", np.float32, (n, 1)),
            hashes=mm("h.u32", np.uint32, (n,)) if has_hashes else None,
        )
    except OSError:
        return None


def cache_size_bytes(cache_dir: str) -> int:
    """Total bytes of committed cache entries (temp files excluded)."""
    total = 0
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return 0
    for name in names:
        if ".tmp." in name:
            continue
        try:
            total += os.path.getsize(os.path.join(cache_dir, name))
        except OSError:
            continue
    return total


#: temp files / orphan slabs younger than this are assumed to belong to an
#: in-flight writer and are left alone by prune_cache
_ORPHAN_MIN_AGE_S = 3600.0


def prune_cache(cache_dir: str, max_bytes: int) -> int:
    """Evict whole entries, oldest meta-mtime first, until the cache fits
    ``max_bytes``; also sweep stale debris — ``.tmp.`` files from writers
    that died without abort() (SIGKILL mid-write) and slabs orphaned
    between commit()'s slab renames and the meta publish — once older than
    an hour.  Returns committed entries removed.  Safe against concurrent
    readers on POSIX: an open memmap keeps its data reachable after
    unlink; the entry simply stops being discoverable."""
    import time

    try:
        names = os.listdir(cache_dir)
    except OSError:
        return 0
    metas = [n for n in names if n.endswith(".meta.json")]
    keys = {m[: -len(".meta.json")] for m in metas}
    now = time.time()
    for name in names:
        if name.endswith(".meta.json"):
            continue
        stale = ".tmp." in name or name.split(".", 1)[0] not in keys
        if not stale:
            continue
        p = os.path.join(cache_dir, name)
        try:
            if now - os.path.getmtime(p) >= _ORPHAN_MIN_AGE_S:
                os.unlink(p)
        except OSError:
            continue
    entries = []
    for meta in metas:
        key = meta[: -len(".meta.json")]
        paths = [os.path.join(cache_dir, meta)] + [
            os.path.join(cache_dir, f"{key}.{s}")
            for s in _SLABS
            if os.path.exists(os.path.join(cache_dir, f"{key}.{s}"))
        ]
        try:
            with open(paths[0], "r", encoding="utf-8") as f:
                version = json.load(f).get("version")
        except (OSError, json.JSONDecodeError):
            version = None
        if version is None or (
            isinstance(version, int) and version < CACHE_VERSION
        ):
            # superseded/corrupt entry: unreadable by this binary's lookup,
            # so it would sit on disk forever — drop it regardless of the
            # budget.  NEWER versions are left alone: during a rolling
            # upgrade two binaries may share a cache_dir, and mutual
            # eviction would defeat the cache for both.
            for p in paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            continue
        try:
            mtime = os.path.getmtime(paths[0])
            size = sum(os.path.getsize(p) for p in paths)
        except OSError:
            continue
        entries.append((mtime, size, paths))
    total = sum(e[1] for e in entries)
    removed = 0
    for mtime, size, paths in sorted(entries):
        if total <= max_bytes:
            break
        # meta first: the entry disappears atomically from lookup's view
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        total -= size
        removed += 1
    return removed


class ShardCacheWriter:
    """Streaming writer for one shard's cache entry.

    ``append`` takes finalized blocks in stream order; ``commit`` makes the
    entry visible atomically (slabs renamed first, meta last).  Anything
    short of commit leaves no visible entry.
    """

    def __init__(self, cache_dir: str, src_path: str, schema: RecordSchema,
                 salt: int, feature_dtype: str = "float32"):
        self.key = cache_key(src_path, schema, salt, feature_dtype)
        self.ok = self.key is not None
        if not self.ok:
            return
        os.makedirs(cache_dir, exist_ok=True)
        self.cache_dir = cache_dir
        self.src_path = src_path
        self.n_features = schema.num_features
        self.feature_dtype = feature_dtype or "float32"
        self._x_dtype = _feature_dtype(feature_dtype)
        self._slabs = (_feature_slab(feature_dtype), "y.f32", "w.f32",
                       "h.u32")
        self.n_rows = 0
        self.has_hashes: bool | None = None
        self._suffix = (
            f".tmp.{os.getpid()}.{threading.get_ident()}.{next(_WRITER_SEQ)}"
        )
        self._tmp = {s: os.path.join(cache_dir, f"{self.key}.{s}{self._suffix}")
                     for s in self._slabs}
        self._files = {s: open(p, "wb") for s, p in self._tmp.items()}

    def append(self, block: ParsedBlock, hashes: np.ndarray | None) -> None:
        if not self.ok:
            return
        if self.has_hashes is None:
            self.has_hashes = hashes is not None
        elif self.has_hashes != (hashes is not None):
            # mixed availability would desync the hash slab; drop the entry
            self.abort()
            return
        np.ascontiguousarray(block.features, self._x_dtype).tofile(
            self._files[self._slabs[0]])
        np.ascontiguousarray(block.targets, np.float32).tofile(
            self._files["y.f32"])
        np.ascontiguousarray(block.weights, np.float32).tofile(
            self._files["w.f32"])
        if hashes is not None:
            np.ascontiguousarray(hashes, np.uint32).tofile(self._files["h.u32"])
        self.n_rows += len(block)

    def commit(self) -> bool:
        if not self.ok:
            return False
        for f in self._files.values():
            f.close()
        for s in self._slabs:
            if s == "h.u32" and not self.has_hashes:
                os.unlink(self._tmp[s])
                continue
            os.replace(self._tmp[s], os.path.join(self.cache_dir,
                                                  f"{self.key}.{s}"))
        meta = {
            "version": CACHE_VERSION,
            "n_rows": self.n_rows,
            "n_features": self.n_features,
            "has_hashes": bool(self.has_hashes),
            "feature_dtype": self.feature_dtype,
            "src": self.src_path,
        }
        meta_tmp = os.path.join(self.cache_dir,
                                f"{self.key}.meta.json{self._suffix}")
        with open(meta_tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f)
        os.replace(meta_tmp,
                   os.path.join(self.cache_dir, f"{self.key}.meta.json"))
        self.ok = False  # single-shot
        return True

    def abort(self) -> None:
        if not getattr(self, "_files", None):
            return
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        for p in self._tmp.values():
            try:
                os.unlink(p)
            except OSError:
                pass
        self.ok = False
