"""PSV record parsing and deterministic train/valid splitting.

Parity surface: the reference's ``load_data`` gunzips PSV shards and parses
every row in a Python loop — target column, selected feature columns and an
optional sample-weight column, negative weights clamped to 1.0, rows routed
to train/valid by ``random.random() >= validRate`` (reference:
ssgd_monitor.py:348-454).

Differences by design:
- parsing is vectorized (numpy block parse; optional C++ fast path in
  ``shifu_tensorflow_tpu.data.native``) instead of per-row Python;
- the train/valid split is **deterministic** (content-hash per row), so a
  restarted or recovered worker sees the identical split — the reference's
  per-process `random.random()` split silently changes membership across
  restarts, which breaks resume semantics (SURVEY.md §7.3);
- ZSCALE normalization can be applied on the fly from ColumnConfig stats,
  matching the serving-side `normtype: ZSCALE` contract
  (ssgd_monitor.py:476-490).
"""

from __future__ import annotations

import re
import zlib
from dataclasses import dataclass, field

import numpy as np

# The one cell grammar both parsers accept: optional space/tab padding,
# optional sign, decimal float (digits/'.'/exponent) or inf/infinity/nan.
# Mirrors parse_cell in cpp/stpu_data.cc exactly; stricter than Python's
# float() (which also takes hex-adjacent spellings like '1_0' underscores
# and unicode digits) so row acceptance cannot depend on which parser ran.
_CELL_RE = re.compile(
    rb"^[ \t]*[+-]?((\d+\.?\d*|\.\d+)(e[+-]?\d+)?|inf(inity)?|nan)[ \t]*$",
    re.IGNORECASE,
)


@dataclass(frozen=True)
class RecordSchema:
    """Which columns mean what in a delimited row (the env-var contract the
    reference's Java side computed: SELECTED_COLUMN_NUMS, TARGET_COLUMN_NUM,
    WEIGHT_COLUMN_NUM — TensorflowTaskExecutor.java:200-238)."""

    feature_columns: tuple[int, ...]
    target_column: int
    weight_column: int = -1  # -1 = no weight column; weights default to 1.0
    delimiter: str = "|"
    # optional ZSCALE stats aligned with feature_columns
    means: tuple[float, ...] = field(default=())
    stds: tuple[float, ...] = field(default=())

    def __post_init__(self) -> None:
        # negative indices would mean "from the end" to Python's list
        # indexing but are an out-of-bounds write to the native parser —
        # reject them up front so both paths agree
        if any(c < 0 for c in self.feature_columns) or self.target_column < 0:
            raise ValueError("feature/target column indices must be >= 0")
        if self.weight_column < -1:
            raise ValueError("weight_column must be >= 0, or -1 for none")

    @property
    def num_features(self) -> int:
        return len(self.feature_columns)

    def with_zscale(self, means, stds) -> "RecordSchema":
        if len(means) != self.num_features or len(stds) != self.num_features:
            raise ValueError("zscale stats must align with feature columns")
        return RecordSchema(
            feature_columns=self.feature_columns,
            target_column=self.target_column,
            weight_column=self.weight_column,
            delimiter=self.delimiter,
            means=tuple(means),
            stds=tuple(stds),
        )


@dataclass
class ParsedBlock:
    features: np.ndarray  # (n, F) float32
    targets: np.ndarray  # (n, 1) float32
    weights: np.ndarray  # (n, 1) float32

    def __len__(self) -> int:
        return self.features.shape[0]

    @staticmethod
    def empty(num_features: int) -> "ParsedBlock":
        return ParsedBlock(
            np.empty((0, num_features), np.float32),
            np.empty((0, 1), np.float32),
            np.empty((0, 1), np.float32),
        )

    @staticmethod
    def concat(blocks: list["ParsedBlock"]) -> "ParsedBlock":
        return ParsedBlock(
            np.concatenate([b.features for b in blocks], axis=0),
            np.concatenate([b.targets for b in blocks], axis=0),
            np.concatenate([b.weights for b in blocks], axis=0),
        )


def _reject():
    raise ValueError("cell outside the shared parser grammar")


def _parse_lines(
    lines: list[bytes], schema: RecordSchema, salt: int, want_hashes: bool
) -> tuple[np.ndarray, np.ndarray | None]:
    """The one pure-Python row-parse loop (the native parsers mirror its
    grammar): wanted-column matrix for every kept row plus (optionally) the
    kept rows' crc32 routing hashes, hash[i] aligned with row i.

    Bad rows (wrong column count / non-numeric cells) are dropped, matching
    the reference's tolerance of unparseable cells (ssgd_monitor.py:404-408)
    but at row granularity so feature vectors never silently shorten.
    """
    delim = schema.delimiter.encode()
    wanted = wanted_columns(schema)
    max_col = max(wanted)
    rows: list[list[float]] = []
    hashes: list[int] = []
    for line in lines:
        cols = line.rstrip(b"\r\n").split(delim)
        if len(cols) <= max_col:
            continue
        try:
            row = [
                float(cols[c]) if _CELL_RE.match(cols[c]) else _reject()
                for c in wanted
            ]
        except ValueError:
            continue
        rows.append(row)
        if want_hashes:
            hashes.append(zlib.crc32(line, salt) & 0xFFFFFFFF)
    arr = (
        np.asarray(rows, dtype=np.float32)
        if rows
        else np.empty((0, len(wanted)), np.float32)
    )
    return arr, (np.asarray(hashes, np.uint32) if want_hashes else None)


def parse_block(lines: list[bytes], schema: RecordSchema) -> ParsedBlock:
    """Parse a block of raw delimited lines into finalized arrays."""
    arr, _ = _parse_lines(lines, schema, 0, want_hashes=False)
    if arr.shape[0] == 0:
        return ParsedBlock.empty(schema.num_features)
    return _finalize(arr, schema)


def _finalize(arr: np.ndarray, schema: RecordSchema) -> ParsedBlock:
    """(n, F+1[+1]) wanted-column matrix -> ParsedBlock with the weight
    clamp and optional ZSCALE applied."""
    nf = schema.num_features
    feats = arr[:, :nf]
    targets = np.ascontiguousarray(arr[:, nf : nf + 1])
    if schema.weight_column >= 0:
        weights = arr[:, nf + 1 : nf + 2].copy()
        # negative weights clamped to 1.0 (parity: ssgd_monitor.py:412-415)
        weights[weights < 0.0] = 1.0
    else:
        weights = np.ones_like(targets)

    if schema.means:
        mu = np.asarray(schema.means, np.float32)
        sd = np.asarray(schema.stds, np.float32)
        sd = np.where(sd == 0.0, 1.0, sd)
        feats = (feats - mu) / sd

    return ParsedBlock(np.ascontiguousarray(feats), targets, weights)


def wanted_columns(schema: RecordSchema) -> tuple[int, ...]:
    """Column extraction order shared by the Python and native parsers."""
    wanted = list(schema.feature_columns) + [schema.target_column]
    if schema.weight_column >= 0:
        wanted.append(schema.weight_column)
    return tuple(wanted)


def split_buffer_lines(buf: bytes) -> list[bytes]:
    """Split strictly on '\\n' (keeping it), matching file iteration —
    unlike bytes.splitlines, which also breaks on \\r/\\v/\\f and would
    change both row boundaries and routing hashes."""
    lines = [chunk + b"\n" for chunk in buf.split(b"\n")]
    if lines:
        lines[-1] = lines[-1][:-1]  # last line keeps no invented newline
        if not lines[-1]:
            lines.pop()
    return lines


def parse_lines_full(
    buf: bytes, schema: RecordSchema, salt: int, want_hashes: bool
) -> tuple[np.ndarray, np.ndarray | None]:
    """Pure-Python mirror of the native parsers' full-block contract, over
    a raw byte buffer."""
    return _parse_lines(split_buffer_lines(buf), schema, salt, want_hashes)


def routing_threshold(valid_rate: float) -> np.uint64:
    """The single train/valid routing rule: a row goes to validation iff
    crc32(line, salt) < valid_rate * 2**32 (compare in uint64 — at
    valid_rate=1.0 the threshold exceeds uint32).  Every splitter — Python
    line loop, native block parser, ShardStream routing — must use this."""
    return np.uint64(int(valid_rate * 0x100000000))


def route_is_valid(hashes: np.ndarray, valid_rate: float) -> np.ndarray:
    """Vectorized routing mask: True where the row belongs to validation."""
    return hashes.astype(np.uint64) < routing_threshold(valid_rate)


def parse_buffer_split(
    buf: bytes,
    schema: RecordSchema,
    valid_rate: float,
    salt: int = 0,
) -> tuple[ParsedBlock, ParsedBlock]:
    """Parse a block of decompressed shard bytes and route rows into
    (train, valid) by the deterministic crc32 hash.

    The native path (cpp/stpu_data.cc via data.native) parses the whole
    buffer with the GIL released and returns per-row hashes; the fallback
    splits lines in Python and reuses ``parse_block``.  Both route by
    crc32 of the raw line bytes (newline included), so the split membership
    is identical regardless of which path ran.
    """
    from shifu_tensorflow_tpu.data import native

    parsed = native.parse_buffer(
        buf,
        wanted_columns(schema),
        schema.delimiter,
        salt=salt,
        want_hashes=valid_rate > 0.0,
    )
    if parsed is not None:
        arr, hashes = parsed
        if valid_rate <= 0.0 or hashes is None:
            return _finalize(arr, schema), ParsedBlock.empty(schema.num_features)
        is_valid = route_is_valid(hashes, valid_rate)
        return (
            _finalize(arr[~is_valid], schema),
            _finalize(arr[is_valid], schema),
        )

    tr, va = split_train_valid(split_buffer_lines(buf), valid_rate, salt)
    return parse_block(tr, schema), parse_block(va, schema)


def split_train_valid(
    lines: list[bytes], valid_rate: float, salt: int = 0
) -> tuple[list[bytes], list[bytes]]:
    """Deterministic per-row routing: crc32(line, salt) maps each row to
    [0,1); rows below ``valid_rate`` go to validation.  Replaces the
    reference's nondeterministic ``random.random() >= validRate``
    (ssgd_monitor.py:396)."""
    if valid_rate <= 0.0:
        return list(lines), []
    train, valid = [], []
    threshold = int(routing_threshold(valid_rate))
    for line in lines:
        h = zlib.crc32(line, salt) & 0xFFFFFFFF
        (valid if h < threshold else train).append(line)
    return train, valid
