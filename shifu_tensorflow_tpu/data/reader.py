"""PSV record parsing and deterministic train/valid splitting.

Parity surface: the reference's ``load_data`` gunzips PSV shards and parses
every row in a Python loop — target column, selected feature columns and an
optional sample-weight column, negative weights clamped to 1.0, rows routed
to train/valid by ``random.random() >= validRate`` (reference:
ssgd_monitor.py:348-454).

Differences by design:
- parsing is vectorized (numpy block parse; optional C++ fast path in
  ``shifu_tensorflow_tpu.data.native``) instead of per-row Python;
- the train/valid split is **deterministic** (content-hash per row), so a
  restarted or recovered worker sees the identical split — the reference's
  per-process `random.random()` split silently changes membership across
  restarts, which breaks resume semantics (SURVEY.md §7.3);
- ZSCALE normalization can be applied on the fly from ColumnConfig stats,
  matching the serving-side `normtype: ZSCALE` contract
  (ssgd_monitor.py:476-490).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class RecordSchema:
    """Which columns mean what in a delimited row (the env-var contract the
    reference's Java side computed: SELECTED_COLUMN_NUMS, TARGET_COLUMN_NUM,
    WEIGHT_COLUMN_NUM — TensorflowTaskExecutor.java:200-238)."""

    feature_columns: tuple[int, ...]
    target_column: int
    weight_column: int = -1  # -1 = no weight column; weights default to 1.0
    delimiter: str = "|"
    # optional ZSCALE stats aligned with feature_columns
    means: tuple[float, ...] = field(default=())
    stds: tuple[float, ...] = field(default=())

    @property
    def num_features(self) -> int:
        return len(self.feature_columns)

    def with_zscale(self, means, stds) -> "RecordSchema":
        if len(means) != self.num_features or len(stds) != self.num_features:
            raise ValueError("zscale stats must align with feature columns")
        return RecordSchema(
            feature_columns=self.feature_columns,
            target_column=self.target_column,
            weight_column=self.weight_column,
            delimiter=self.delimiter,
            means=tuple(means),
            stds=tuple(stds),
        )


@dataclass
class ParsedBlock:
    features: np.ndarray  # (n, F) float32
    targets: np.ndarray  # (n, 1) float32
    weights: np.ndarray  # (n, 1) float32

    def __len__(self) -> int:
        return self.features.shape[0]

    @staticmethod
    def empty(num_features: int) -> "ParsedBlock":
        return ParsedBlock(
            np.empty((0, num_features), np.float32),
            np.empty((0, 1), np.float32),
            np.empty((0, 1), np.float32),
        )

    @staticmethod
    def concat(blocks: list["ParsedBlock"]) -> "ParsedBlock":
        return ParsedBlock(
            np.concatenate([b.features for b in blocks], axis=0),
            np.concatenate([b.targets for b in blocks], axis=0),
            np.concatenate([b.weights for b in blocks], axis=0),
        )


def parse_block(lines: list[bytes], schema: RecordSchema) -> ParsedBlock:
    """Parse a block of raw delimited lines into arrays.

    Bad rows (wrong column count / non-numeric cells) are dropped, matching
    the reference's tolerance of unparseable cells (ssgd_monitor.py:404-408)
    but at row granularity so feature vectors never silently shorten.
    """
    if not lines:
        return ParsedBlock.empty(schema.num_features)

    delim = schema.delimiter.encode()
    wanted = list(schema.feature_columns) + [schema.target_column]
    if schema.weight_column >= 0:
        wanted.append(schema.weight_column)
    max_col = max(wanted)

    rows: list[list[float]] = []
    for line in lines:
        cols = line.rstrip(b"\r\n").split(delim)
        if len(cols) <= max_col:
            continue
        try:
            rows.append([float(cols[c]) for c in wanted])
        except ValueError:
            continue

    if not rows:
        return ParsedBlock.empty(schema.num_features)

    arr = np.asarray(rows, dtype=np.float32)
    nf = schema.num_features
    feats = arr[:, :nf]
    targets = arr[:, nf : nf + 1]
    if schema.weight_column >= 0:
        weights = arr[:, nf + 1 : nf + 2].copy()
        # negative weights clamped to 1.0 (parity: ssgd_monitor.py:412-415)
        weights[weights < 0.0] = 1.0
    else:
        weights = np.ones_like(targets)

    if schema.means:
        mu = np.asarray(schema.means, np.float32)
        sd = np.asarray(schema.stds, np.float32)
        sd = np.where(sd == 0.0, 1.0, sd)
        feats = (feats - mu) / sd

    return ParsedBlock(np.ascontiguousarray(feats), targets, weights)


def split_train_valid(
    lines: list[bytes], valid_rate: float, salt: int = 0
) -> tuple[list[bytes], list[bytes]]:
    """Deterministic per-row routing: crc32(line, salt) maps each row to
    [0,1); rows below ``valid_rate`` go to validation.  Replaces the
    reference's nondeterministic ``random.random() >= validRate``
    (ssgd_monitor.py:396)."""
    if valid_rate <= 0.0:
        return list(lines), []
    train, valid = [], []
    threshold = int(valid_rate * 0x100000000)
    for line in lines:
        h = zlib.crc32(line, salt) & 0xFFFFFFFF
        (valid if h < threshold else train).append(line)
    return train, valid
