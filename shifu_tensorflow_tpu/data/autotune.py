"""Obs-driven ingest autotuner — sizes the pipeline from live span ratios.

The tf.data paper's core result (PAPERS.md) is that hand-set
parallelism/prefetch knobs lose to a feedback loop reading the pipeline's
own timing.  This is that loop for the staged ingest pipeline
(data/pipeline.py): after every epoch it reads the stage accounting the
stream collected (``StageStats`` — reader busy, decode busy, consumer
starvation) plus, when the obs plane is tracing, the step-phase summary
from the installed tracer (``step.infeed.wait`` / ``step.dispatch``), and
adjusts ONE knob for the next epoch:

- **starved** (consumer waited on ingest for more than ``starve_hi`` of
  the epoch): widen the binding stage — the decode pool when its busy
  fraction dominates (host-bound: parse/finalize is the constraint), else
  the readers (infeed-bound: IO/inflate is the constraint).  When both
  stages look idle yet the consumer still stalls, the gap is placement
  burstiness — deepen prefetch.
- **balanced** (starvation under ``starve_lo``): converged; stop.  No
  oscillation by construction: one dimension moves per epoch, growth is
  +1 step bounded by per-dimension caps.
- **regret rollback**: a widening must pay for itself in measured epoch
  throughput (rows/s from the stream's own accounting).  If the next
  epoch is not faster than the pre-widening epoch by ``IMPROVE_EPS``,
  the knob reverts and the dimension retires — on a host whose cores
  are already saturated, blindly widening walks PAST the optimum into
  oversubscription (more threads than deliverable cores = scheduler
  thrash, measured slower), which is exactly the hand-tuning failure
  the tf.data feedback loop exists to avoid.  A retired dimension is
  re-eligible only if starvation later rises above ``starve_hi`` again
  with every other dimension also blocked (host conditions changed).
  The check is SKIPPED (knob kept, no strike) when the cache-served
  fraction shifted by more than 25 points between the two epochs — a
  cold→warm (or eviction) transition moves rows/s severalfold on its
  own, and the verdict would measure cache state, not the knob.

Explicit knobs pin their dimension: a CLI/conf-set value is an operator
statement the tuner must not override (``shifu.tpu.data-*`` keys,
docs/ingest.md).  The decision log (``history``) rides into the obs
journal via the trainer's epoch events so a tuned run is auditable.
"""

from __future__ import annotations

import os
from dataclasses import replace
from typing import Iterable

from shifu_tensorflow_tpu.data.pipeline import IngestKnobs, StageStats
from shifu_tensorflow_tpu.utils import logs

log = logs.get("ingest.autotune")

#: consumer-starvation fraction above which the tuner acts
STARVE_HI = 0.10
#: ... and below which the pipeline counts as balanced (converged)
STARVE_LO = 0.05
#: a stage whose busy fraction exceeds this is the binding constraint
BUSY_HI = 0.60
#: minimum epoch-rate improvement for a widening to stick; below it the
#: knob reverts (rate noise on a shared host argues for a SMALL positive
#: margin: a false revert keeps a config measured no worse, a false keep
#: leaves one extra thread — both cheap)
IMPROVE_EPS = 0.02


class IngestAutotuner:
    """Per-trainer feedback controller over (readers, decode_workers,
    prefetch).  Thread-compatible with the trainer's single-threaded epoch
    loop — ``settings()`` at stream build, ``note_stats()`` from the
    stream's close, ``observe_epoch()`` between epochs."""

    def __init__(
        self,
        initial: IngestKnobs,
        *,
        pinned: Iterable[str] = (),
        max_readers: int | None = None,
        max_decode: int | None = None,
        max_prefetch: int = 8,
        cpu_count: int | None = None,
    ):
        cpus = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
        self.knobs = initial
        self.pinned = frozenset(pinned)
        # readers beyond ~2x cores only help when reads block on remote
        # IO; decode is pure CPU so its cap is the core count
        self.max_readers = max_readers or max(4, 2 * cpus)
        self.max_decode = max_decode or max(1, cpus)
        self.max_prefetch = max_prefetch
        self.converged = False
        self.history: list[dict] = []
        self._last_stats: StageStats | None = None
        #: (dimension, knobs-before-widen, rate-before-widen,
        #: cache-fraction-before-widen) awaiting the regret check
        #: against the NEXT epoch's measured rate
        self._pending: "tuple[str, IngestKnobs, float, float] | None" = None
        #: dimensions retired by a failed widening (regret rollback) and
        #: the per-dimension failure count.  A once-failed dimension may
        #: be re-probed a single time (host conditions can change
        #: mid-job); a second failure retires it for good — the
        #: widen/revert cycle is bounded, never a thrash loop.
        self._retired: set[str] = set()
        self._reverts: dict[str, int] = {}

    # ---- inputs ----
    def settings(self) -> IngestKnobs:
        return self.knobs

    def note_stats(self, stats: StageStats) -> None:
        """Stats sink for the TRAIN stream (ShardStream ``stats_sink``)."""
        self._last_stats = stats

    # ---- the policy ----
    def observe_epoch(self, step_summary: dict | None = None) -> IngestKnobs:
        """Digest the finished epoch; returns the knobs for the next one.

        ``step_summary`` is the installed tracer's span summary (may be
        None when obs is off — the pipeline's own StageStats carry the
        primary signal either way)."""
        stats = self._last_stats
        self._last_stats = None
        if stats is None or stats.wall_s <= 0.0:
            return self.knobs
        frac = stats.busy_fractions()
        starve = frac["wait_frac"]
        # prefer the tracer's consumer-side infeed wait when present: it
        # measures the stall where it hurts (the training loop), while
        # the pipeline's wait_s is measured at the sequencer — upstream
        # of the put stage
        if step_summary:
            w = step_summary.get("step.infeed.wait")
            epoch_wall = stats.wall_s
            if w and epoch_wall > 0:
                # step.* spans measure 1/sampled_every of the real events
                # (obs-trace-sample) — scale back to an absolute total
                # before dividing by the (unsampled) wall clock, exactly
                # as budget_fields does, or sampling would understate
                # starvation by the sample factor
                wait_total = w["total_s"] * w.get("sampled_every", 1)
                starve = max(starve, min(1.0, wait_total / epoch_wall))

        rate = stats.rows / stats.wall_s
        cache_frac = stats.cache_chunks / max(1, stats.chunks)
        decision = {"starve": round(starve, 4),
                    "read_busy": round(frac["read_busy"], 4),
                    "decode_busy": round(frac["decode_busy"], 4),
                    "rows_per_s": round(rate, 0),
                    "cache_frac": round(cache_frac, 2),
                    "knobs": (self.knobs.readers, self.knobs.decode_workers,
                              self.knobs.prefetch)}
        # regret check first: the previous epoch's widening must have paid
        # for itself in measured throughput, or the knob reverts and the
        # dimension retires (oversubscription measures SLOWER, not just
        # flat — walking past the optimum is the failure mode here)
        if self._pending is not None:
            dim, prev_knobs, prev_rate, prev_cache = self._pending
            self._pending = None
            if abs(cache_frac - prev_cache) > 0.25:
                # the source changed under the comparison: a cold->warm
                # transition (first epoch parses text, second streams
                # memmap'd cache blocks severalfold faster) or a mid-job
                # eviction in the other direction moves rows/s far more
                # than any one-step widening — the verdict would reflect
                # cache state, not the knob.  Keep the knob provisionally
                # and spend no revert strike; the normal policy below
                # re-evaluates from this epoch's (same-source) baseline.
                decision["action"] = f"regret-skip-{dim}"
                self.history.append(decision)
                return self.knobs
            if prev_rate > 0 and rate < prev_rate * (1.0 + IMPROVE_EPS):
                self.knobs = prev_knobs
                self._retired.add(dim)
                self._reverts[dim] = self._reverts.get(dim, 0) + 1
                decision["action"] = f"revert-{dim}"
                self.history.append(decision)
                log.info("ingest autotune: revert %s (%.0f -> %.0f "
                         "rows/s, below +%.0f%%)", dim, prev_rate, rate,
                         100 * IMPROVE_EPS)
                return self.knobs
        if starve < STARVE_LO:
            self.converged = True
            decision["action"] = "balanced"
        elif starve < STARVE_HI:
            # the dead band holds UNCONDITIONALLY, converged or not:
            # widening on noise-level starvation can't earn its 2% regret
            # margin, and the failed attempt would burn one of the
            # dimension's two revert strikes — permanently retiring it
            # before the job ever becomes genuinely starved
            decision["action"] = "hold"
        else:
            self.converged = False
            decision["action"] = self._widen(frac, rate, cache_frac)
        self.history.append(decision)
        if decision["action"] not in ("balanced", "hold", "pinned"):
            log.info("ingest autotune: %s -> readers=%d decode=%d "
                     "prefetch=%d (starve=%.0f%%)", decision["action"],
                     self.knobs.readers, self.knobs.decode_workers,
                     self.knobs.prefetch, 100 * starve)
        return self.knobs

    def _widen(self, frac: dict[str, float], rate: float,
               cache_frac: float) -> str:
        k = self.knobs
        blocked = self.pinned | self._retired
        decode_bound = (frac["decode_busy"] >= frac["read_busy"]
                        and frac["decode_busy"] > BUSY_HI)
        read_bound = frac["read_busy"] > BUSY_HI
        if decode_bound and "decode_workers" not in blocked \
                and k.decode_workers < self.max_decode:
            self.knobs = replace(k, decode_workers=k.decode_workers + 1)
            self._pending = ("decode_workers", k, rate, cache_frac)
            return "widen-decode"
        if read_bound and "readers" not in blocked \
                and k.readers < self.max_readers:
            self.knobs = replace(k, readers=k.readers + 1)
            self._pending = ("readers", k, rate, cache_frac)
            return "widen-readers"
        # neither stage saturated (or both pinned/capped) yet the consumer
        # starves: the batches exist but arrive bursty — deepen the device
        # put pipeline
        if "prefetch" not in blocked and k.prefetch < self.max_prefetch:
            self.knobs = replace(k, prefetch=k.prefetch + 1)
            self._pending = ("prefetch", k, rate, cache_frac)
            return "deepen-prefetch"
        # starved with every dimension pinned, retired, or at cap.  A
        # once-failed dimension gets one re-probe (host conditions change
        # mid-job); a twice-failed dimension stays retired — the cycle is
        # bounded.  The actual widen happens next starved epoch with the
        # retirement lifted; this epoch just lifts it.
        retryable = {d for d in self._retired
                     if self._reverts.get(d, 0) < 2 and d not in self.pinned}
        if retryable:
            self._retired -= retryable
            return "reprobe"
        return "pinned"


def resolve_ingest_knobs(
    readers: int | None,
    decode_workers: int | None,
    prefetch: int | None,
    *,
    autotune: bool = True,
    fallback_prefetch: int = 2,
    cpu_count: int | None = None,
) -> tuple[IngestKnobs, "IngestAutotuner | None"]:
    """Turn resolved knob values (None/0 = auto) into (initial knobs,
    autotuner-or-None).  An explicitly set knob both seeds its dimension
    and PINS it — the operator's value wins over the tuner for that
    dimension while the others keep adapting; with autotune off the
    initial knobs are simply final."""
    from shifu_tensorflow_tpu.data.pipeline import default_knobs

    auto = default_knobs(cpu_count)
    pinned = set()
    r = auto.readers
    if readers:
        r = int(readers)
        pinned.add("readers")
    d = auto.decode_workers
    if decode_workers:
        d = int(decode_workers)
        pinned.add("decode_workers")
    p = fallback_prefetch
    if prefetch:
        p = int(prefetch)
        pinned.add("prefetch")
    knobs = IngestKnobs(readers=max(1, r), decode_workers=max(1, d),
                        prefetch=max(1, p))
    if not autotune:
        return knobs, None
    return knobs, IngestAutotuner(knobs, pinned=pinned,
                                  cpu_count=cpu_count)


def install_ingest_autotuner(trainer, readers, decode_workers, prefetch,
                             *, autotune: bool, fallback_prefetch: int):
    """Resolve the staged-ingest knobs, install the tuner (or None, with
    autotune off) on ``trainer``, seed its device-put depth, and return
    ``(widths, stats_sink)``: the per-epoch stream factories call
    ``widths()`` for the CURRENT reader/decode widths (the tuner may have
    resized them since last epoch), and the TRAIN stream feeds its
    ``StageStats`` into ``stats_sink`` (None when there is no tuner).
    The ONE wiring helper both the single-process CLI and the fleet
    worker use, so the two paths resolve ``shifu.tpu.data-*`` the same
    way by construction."""
    knobs, tuner = resolve_ingest_knobs(
        readers, decode_workers, prefetch,
        autotune=autotune, fallback_prefetch=fallback_prefetch,
    )
    trainer.ingest_autotuner = tuner
    trainer.prefetch_depth = max(1, knobs.prefetch)

    def widths() -> dict:
        k = tuner.settings() if tuner is not None else knobs
        return {"n_readers": k.readers, "decode_workers": k.decode_workers}

    return widths, (tuner.note_stats if tuner is not None else None)
