"""Batching and host→device infeed.

The reference loads every shard fully into Python lists and slices them with
``np.array_split`` per epoch (ssgd_monitor.py:348-454) — nonviable at the
1B-row target (SURVEY.md §7.2 item 1).  Here the input path is built for
TPU from the start:

- **fixed batch shapes**: every batch is exactly ``batch_size`` rows; the
  final partial batch is zero-padded with ``weight=0`` rows so the padded
  rows contribute nothing to the weighted loss and XLA sees one static
  shape (no recompilation, MXU-friendly);
- **streaming**: ``ShardStream`` reads+parses blocks on a background thread
  into a bounded queue, overlapping host IO/decompression with device step
  time;
- **prefetch to device**: ``prefetch_to_device`` keeps ``depth`` batches
  resident ahead of the consumer via ``jax.device_put``.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Sequence

import numpy as np

from shifu_tensorflow_tpu.data.reader import (
    ParsedBlock,
    RecordSchema,
    parse_buffer_split,
    wanted_columns,
)
from shifu_tensorflow_tpu.utils import fs

Batch = dict[str, np.ndarray]  # {"x": (B,F), "y": (B,1), "w": (B,1)}


def resolve_stream_feature_dtype(setting: str | None, *,
                                 uses_feature_hashing: bool,
                                 has_normalization_stats: bool = True) -> str:
    """Streaming TRANSPORT dtype for features (conf key
    shifu.tpu.stream-feature-dtype), decoupled from the compute dtype.

    ``auto`` (the default) ships bf16 whenever it is safe: half the cache
    slab bytes and 4.6× the fp32 host→device rate measured through the
    tunneled backend (BENCH_TRANSFER.json); the jitted step widens back to
    the params' precision on device (train/trainer.py _widen_features), so
    an fp32 model still computes fp32 — bf16 is transport-only.

    Two unsafe cases keep ``auto`` at float32:

    - models that HASH feature columns (embedding / wide-cross): bucket
      ids are computed from raw float bits; bf16 rounding of category
      codes > 256 would re-bucket them, skewing training against the
      f32-hashing exported scorer.  An explicit bfloat16 request refuses
      loudly rather than silently skewing;
    - no ZSCALE normalization stats (``has_normalization_stats=False``):
      z-scaled features are O(1) where bf16's 8-bit mantissa is plenty,
      but RAW features (un-normalized numeric codes, large-magnitude
      amounts fed densely) lose low-order digits with no warning — the
      KS-parity evidence behind the bf16 default only covers normalized
      pipelines.  An explicit ``bfloat16`` still forces it (the operator
      owns the precision claim); ``auto`` stays conservative.
    """
    s = (setting or "auto").lower()
    if s == "auto":
        if uses_feature_hashing or not has_normalization_stats:
            return "float32"
        return "bfloat16"
    if s == "bfloat16" and uses_feature_hashing:
        raise ValueError(
            "shifu.tpu.stream-feature-dtype=bfloat16 is unsafe with "
            "hashed feature columns: bucket ids are computed from raw "
            "float bits, and bf16 rounding re-buckets category codes "
            "> 256 — use auto (streams float32 for hashing models)"
        )
    if s not in ("float32", "bfloat16"):
        raise ValueError(
            f"unknown stream-feature-dtype {setting!r} "
            "(auto | float32 | bfloat16)"
        )
    return s

# reader-thread end marker: (_TAIL, leftover ParsedBlock)
_TAIL = object()


def make_batch(x: np.ndarray, y: np.ndarray, w: np.ndarray) -> Batch:
    return {"x": x, "y": y, "w": w}


def pad_to_batch(block: ParsedBlock, batch_size: int) -> ParsedBlock:
    """Zero-pad rows to a multiple of batch_size with weight=0 rows."""
    n = len(block)
    rem = n % batch_size
    if rem == 0 and n > 0:
        return block
    pad = batch_size - rem if n > 0 else batch_size
    # padding keeps the block's feature dtype: a float32 pad concatenated
    # onto bfloat16 features would silently promote the whole batch
    f = np.zeros((pad, block.features.shape[1]), block.features.dtype)
    z = np.zeros((pad, 1), np.float32)
    return ParsedBlock.concat([block, ParsedBlock(f, z, z)])


def iter_batches(block: ParsedBlock, batch_size: int, *, shuffle: bool = False,
                 seed: int = 0) -> Iterator[Batch]:
    """Slice an in-memory block into fixed-size batches."""
    if len(block) == 0:
        return
    if shuffle:
        perm = np.random.default_rng(seed).permutation(len(block))
        block = ParsedBlock(
            block.features[perm], block.targets[perm], block.weights[perm]
        )
    padded = pad_to_batch(block, batch_size)
    for i in range(0, len(padded), batch_size):
        sl = slice(i, i + batch_size)
        yield make_batch(padded.features[sl], padded.targets[sl], padded.weights[sl])


@dataclass
class InMemoryDataset:
    """Fully-loaded shard with deterministic train/valid split — the
    reference ``load_data`` contract (ssgd_monitor.py:348-454) for datasets
    that fit in host RAM (the demo / unit-test path)."""

    train: ParsedBlock
    valid: ParsedBlock
    schema: RecordSchema

    @classmethod
    def load(
        cls,
        paths: Sequence[str],
        schema: RecordSchema,
        valid_rate: float,
        salt: int = 0,
    ) -> "InMemoryDataset":
        train_blocks, valid_blocks = [], []
        for path in paths:
            with fs.open_maybe_gzip(path) as f:
                buf = f.read()
            tr, va = parse_buffer_split(buf, schema, valid_rate, salt)
            train_blocks.append(tr)
            valid_blocks.append(va)
        if not train_blocks:
            empty = ParsedBlock.empty(schema.num_features)
            return cls(empty, empty, schema)
        return cls(
            ParsedBlock.concat(train_blocks),
            ParsedBlock.concat(valid_blocks),
            schema,
        )

    def train_batches(self, batch_size: int, *, epoch: int = 0) -> Iterator[Batch]:
        return iter_batches(self.train, batch_size, shuffle=True, seed=epoch)

    def valid_batches(self, batch_size: int) -> Iterator[Batch]:
        return iter_batches(self.valid, batch_size)

    def train_batches_fixed(
        self, batch_size: int, steps: int, *, epoch: int = 0
    ) -> Iterator[Batch]:
        """Exactly ``steps`` batches (zero-weight padded) — SPMD epochs."""
        return fixed_step_batches(
            iter_batches(self.train, batch_size, shuffle=True, seed=epoch),
            batch_size, steps, self.schema.num_features,
        )

    def valid_batches_fixed(self, batch_size: int, steps: int) -> Iterator[Batch]:
        return fixed_step_batches(
            iter_batches(self.valid, batch_size),
            batch_size, steps, self.schema.num_features,
        )

    def steps_per_epoch(self, batch_size: int) -> int:
        return -(-len(self.train) // batch_size)

    def valid_steps(self, batch_size: int) -> int:
        return -(-len(self.valid) // batch_size)


def _zero_batch(batch_size: int, num_features: int,
                x_dtype=np.float32) -> Batch:
    """All-padding batch: weight 0 everywhere, so it contributes nothing to
    the weighted loss/gradient — pure barrier participation.  ``x_dtype``
    must match the real batches' feature dtype or SPMD processes would
    compile different programs for padded vs real steps."""
    z = np.zeros((batch_size, 1), np.float32)
    return make_batch(np.zeros((batch_size, num_features), x_dtype), z, z)


def fixed_step_batches(
    batches: Iterable[Batch],
    batch_size: int,
    steps: int,
    num_features: int,
    *,
    on_dropped: Callable[[int], None] | None = None,
    x_dtype=np.float32,
) -> Iterator[Batch]:
    """Adapt any batch iterator to EXACTLY ``steps`` batches of exactly
    ``batch_size`` rows.

    Under cross-process SPMD every process must execute the same number of
    identically-shaped steps per epoch or the collective deadlocks (XLA
    all-reduce is a barrier; the reference had the same constraint spread
    across SyncReplicasOptimizer's token queue, ssgd_monitor.py:136-142).
    Shards are rarely equal-sized, so the coordinator agrees on the MAX step
    count and shorter shards pad with zero-weight batches; a source yielding
    more than ``steps`` batches has the surplus dropped (``on_dropped``
    receives the dropped row count — callers log it; silent truncation reads
    as full coverage when it isn't).
    """
    it = iter(batches)
    emitted = 0
    for batch in it:
        if emitted >= steps:
            dropped = int(batch["x"].shape[0])
            for extra in it:
                dropped += int(extra["x"].shape[0])
            if on_dropped is not None and dropped:
                on_dropped(dropped)
            return
        n = batch["x"].shape[0]
        if n != batch_size:  # pad a short (final) batch to the fixed shape
            pad = batch_size - n
            batch = {
                k: np.concatenate(
                    [np.asarray(v), np.zeros((pad,) + v.shape[1:], v.dtype)]
                )
                for k, v in batch.items()
            }
        yield batch
        emitted += 1
    while emitted < steps:
        yield _zero_batch(batch_size, num_features, x_dtype)
        emitted += 1


class ShardStream:
    """Background streaming reader: files → parsed blocks → fixed batches.

    ``n_readers`` threads split the file list and fill one bounded queue of
    fixed-size batches; the consumer (training loop) drains it.  Each file
    is served from the fastest available source, in order:

    1. **binary cache hit** (``cache_dir`` set, entry valid): finalized
       tensors are memory-mapped and batches are zero-copy views — ingest
       at page-cache speed, the steady-state multi-epoch path
       (data/cache.py);
    2. **fused native stream** (local file, native lib built): one C++ pass
       does read→inflate→parse (cpp/stpu_data.cc stpu_stream_*) with the
       GIL released; a cache entry is written as a side effect when
       ``cache_dir`` is set;
    3. **byte-chunk fallback** (remote schemes / no native lib): fs-layer
       reads + block parse, the original path.

    Determinism: row→train/valid membership is per-row content hashing and
    independent of reader count and of which source served the file; with
    ``n_readers > 1`` the *order* in which batches arrive (and batch
    composition at file boundaries) depends on thread interleaving, so the
    default stays at 1 reader — fully reproducible — and parallel ingest
    is an explicit opt-in for hosts with cores to spare.
    """

    def __init__(
        self,
        paths: Sequence[str],
        schema: RecordSchema,
        batch_size: int,
        *,
        valid_rate: float = 0.0,
        emit: str = "train",  # which side of the split to emit
        block_bytes: int = 4 << 20,
        queue_depth: int = 8,
        drop_remainder: bool = False,
        salt: int = 0,
        n_readers: int | None = None,
        cache_dir: str | None = None,
        feature_dtype: str = "float32",
    ):
        self.paths = list(paths)
        self.schema = schema
        self.batch_size = batch_size
        self.valid_rate = valid_rate
        self.emit = emit
        self.block_bytes = block_bytes
        self.queue_depth = queue_depth
        self.drop_remainder = drop_remainder
        self.salt = salt
        self.cache_dir = cache_dir
        # "float32" | "bfloat16": emitted batch x dtype; bf16 halves cache
        # slab reads and host->device transfer for bf16 training runs
        self.feature_dtype = feature_dtype or "float32"
        if n_readers is None:
            n_readers = 1
        self.n_readers = max(1, min(n_readers, max(1, len(self.paths))))

    @staticmethod
    def _put_or_stop(q: "queue.Queue", stop: threading.Event, item) -> bool:
        """Bounded put that gives up when the consumer abandoned the
        iterator; a plain q.put could block a daemon thread forever."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce(
        self,
        files: Sequence[str],
        q: "queue.Queue",
        stop: threading.Event,
    ) -> None:
        """One reader thread: emit full batches from its file subset, then a
        ``(_TAIL, leftover ParsedBlock)`` marker the consumer merges."""
        carry = ParsedBlock.empty(self.schema.num_features)
        try:
            for path in files:
                for block, hashes in self._file_blocks(path):
                    carry = self._emit_blocks(
                        q, stop, carry, self._route(block, hashes)
                    )
                    if stop.is_set():
                        return
            self._put_or_stop(q, stop, (_TAIL, carry))
        except Exception as e:  # surface reader errors to the consumer
            self._put_or_stop(q, stop, e)

    # ---- sources ----------------------------------------------------------

    def _file_blocks(self, path: str):
        """Yield (finalized full ParsedBlock, routing hashes|None) for one
        shard, from cache / native stream / byte-chunk fallback."""
        from shifu_tensorflow_tpu.data import cache as shard_cache
        from shifu_tensorflow_tpu.data import native
        from shifu_tensorflow_tpu.data.reader import _finalize

        need_hashes = self.valid_rate > 0.0
        if self.cache_dir is not None:
            reader = shard_cache.lookup(self.cache_dir, path, self.schema,
                                        self.salt, self.feature_dtype)
            if reader is not None and (not need_hashes or reader.has_hashes):
                yield from reader.blocks()
                return

        writer = None
        if self.cache_dir is not None:
            writer = shard_cache.ShardCacheWriter(
                self.cache_dir, path, self.schema, self.salt,
                self.feature_dtype,
            )
        want_hashes = need_hashes or writer is not None

        gen = None
        if "://" not in path or path.startswith("file://"):
            gen = native.stream_blocks(
                fs.strip_local(path), wanted_columns(self.schema),
                self.schema.delimiter, salt=self.salt,
                want_hashes=want_hashes,
            )
        try:
            blocks = (
                gen if gen is not None
                else self._byte_chunk_blocks(path, want_hashes)
            )
            cast = self._cast_features
            for arr, hashes in blocks:
                block = cast(_finalize(arr, self.schema))
                if writer is not None:
                    writer.append(block, hashes)
                yield block, hashes
            if writer is not None:
                writer.commit()
        except BaseException:
            if writer is not None:
                writer.abort()
            raise

    def _byte_chunk_blocks(self, path: str, want_hashes: bool):
        """fs-layer fallback: decompressed byte chunks cut at line
        boundaries, parsed per chunk (native block parser when present,
        pure Python otherwise).  Yields (wanted-matrix, hashes|None)."""
        from shifu_tensorflow_tpu.data import native
        from shifu_tensorflow_tpu.data.reader import parse_lines_full

        wanted = wanted_columns(self.schema)

        def _parse(buf: bytes):
            parsed = native.parse_buffer(
                buf, wanted, self.schema.delimiter,
                salt=self.salt, want_hashes=want_hashes,
            )
            if parsed is None:
                parsed = parse_lines_full(buf, self.schema, self.salt,
                                          want_hashes)
            return parsed

        tail = b""
        with fs.open_maybe_gzip(path) as f:
            while True:
                chunk = f.read(self.block_bytes)
                if not chunk:
                    break
                data = tail + chunk
                cut = data.rfind(b"\n")
                if cut < 0:
                    tail = data
                    continue
                tail = data[cut + 1 :]
                yield _parse(data[: cut + 1])
        if tail:
            yield _parse(tail)

    def _cast_features(self, block: ParsedBlock) -> ParsedBlock:
        """Cast parsed float32 features to the emission dtype (no-op for
        float32); cold parse and warm cache then serve identical values."""
        if self.feature_dtype == "float32":
            return block
        from shifu_tensorflow_tpu.data.cache import _feature_dtype

        return ParsedBlock(
            block.features.astype(_feature_dtype(self.feature_dtype)),
            block.targets, block.weights,
        )

    # ---- routing + batch emission -----------------------------------------

    def _route(self, block: ParsedBlock, hashes) -> ParsedBlock:
        """Select this stream's side of the train/valid split."""
        if self.valid_rate <= 0.0:
            if self.emit == "train":
                return block
            return ParsedBlock.empty(self.schema.num_features)
        if hashes is None:
            raise ValueError("valid_rate > 0 requires routing hashes")
        from shifu_tensorflow_tpu.data.reader import route_is_valid

        is_valid = route_is_valid(hashes, self.valid_rate)
        keep = is_valid if self.emit == "valid" else ~is_valid
        if keep.all():
            return block
        return ParsedBlock(
            block.features[keep], block.targets[keep], block.weights[keep]
        )

    def _emit_blocks(self, q, stop, carry: ParsedBlock,
                     block: ParsedBlock) -> ParsedBlock:
        """Emit fixed-size batches; full batches inside ``block`` are pure
        slices (views — zero copy on the memmap'd cache path); only the
        carry top-up at block boundaries copies rows."""
        B = self.batch_size
        i = 0
        if len(carry):
            take = min(B - len(carry), len(block))
            if take:
                carry = ParsedBlock.concat([
                    carry,
                    ParsedBlock(block.features[:take], block.targets[:take],
                                block.weights[:take]),
                ])
                i = take
            if len(carry) < B:
                return carry
            if not self._put_or_stop(
                q, stop,
                make_batch(carry.features, carry.targets, carry.weights),
            ):
                return ParsedBlock.empty(self.schema.num_features)
            carry = ParsedBlock.empty(self.schema.num_features)
        n_full = i + ((len(block) - i) // B) * B
        for j in range(i, n_full, B):
            sl = slice(j, j + B)
            if not self._put_or_stop(
                q, stop,
                make_batch(block.features[sl], block.targets[sl],
                           block.weights[sl]),
            ):
                return carry
        return ParsedBlock(
            block.features[n_full:], block.targets[n_full:],
            block.weights[n_full:],
        )

    def __iter__(self) -> Iterator[Batch]:
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        stop = threading.Event()
        if self.n_readers == 1:
            buckets = [self.paths]
        else:
            # size-aware assignment (greedy LPT): one huge file must not
            # leave the other readers idle for most of the epoch
            from shifu_tensorflow_tpu.data.splitter import split_size_aware

            buckets = [
                list(s.paths)
                for s in split_size_aware(self.paths, self.n_readers)
            ]
        threads = [
            threading.Thread(
                target=self._produce, args=(files, q, stop), daemon=True
            )
            for files in buckets
            if files
        ]
        for t in threads:
            t.start()
        tails: list[ParsedBlock] = []
        done = 0
        try:
            while done < len(threads):
                item = q.get()
                if isinstance(item, Exception):
                    raise item
                if isinstance(item, tuple) and item[0] is _TAIL:
                    tails.append(item[1])
                    done += 1
                    continue
                yield item
            # merge per-reader leftovers: full batches always stream; only
            # the final sub-batch remainder is dropped under drop_remainder
            # (at most batch_size-1 rows, independent of reader count)
            tails = [t for t in tails if len(t)]
            if tails:
                merged = ParsedBlock.concat(tails) if len(tails) > 1 else tails[0]
                if not self.drop_remainder:
                    merged = pad_to_batch(merged, self.batch_size)
                n_full = (len(merged) // self.batch_size) * self.batch_size
                for i in range(0, n_full, self.batch_size):
                    sl = slice(i, i + self.batch_size)
                    yield make_batch(
                        merged.features[sl], merged.targets[sl],
                        merged.weights[sl],
                    )
        finally:
            stop.set()
            # drain so producers can observe stop and exit
            for t in threads:
                while t.is_alive():
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break


def prefetch_to_device(
    batches: Iterable[Batch],
    put: Callable[[Batch], Batch] | None = None,
    depth: int = 2,
) -> Iterator[Batch]:
    """Keep ``depth`` batches already transferred ahead of the consumer.

    ``put`` maps a host batch to device (default ``jax.device_put``); with a
    ``NamedSharding`` it lands shards directly on the mesh.  This is the
    double-buffered infeed the reference lacked (its feed_dict marshalled
    every batch synchronously — SURVEY.md §3.4 hot-loop finding).
    """
    import collections

    import jax

    if put is None:
        put = jax.device_put

    buf: "collections.deque" = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(buf) < depth:
                buf.append(put(next(it)))
            yield buf.popleft()
    except StopIteration:
        while buf:
            yield buf.popleft()
