"""Batching and host→device infeed.

The reference loads every shard fully into Python lists and slices them with
``np.array_split`` per epoch (ssgd_monitor.py:348-454) — nonviable at the
1B-row target (SURVEY.md §7.2 item 1).  Here the input path is built for
TPU from the start:

- **fixed batch shapes**: every batch is exactly ``batch_size`` rows; the
  final partial batch is zero-padded with ``weight=0`` rows so the padded
  rows contribute nothing to the weighted loss and XLA sees one static
  shape (no recompilation, MXU-friendly);
- **streaming**: ``ShardStream`` fronts the staged pull pipeline
  (data/pipeline.py): parallel shard readers + decode pool + ordered
  sequencer + seeded shuffle buffer, overlapping host IO/decompression/
  parse with device step time while keeping the batch order a pure
  function of (paths, schema, salt) — reproducible at any parallelism;
- **prefetch to device**: ``prefetch_to_device`` keeps ``depth`` batches
  resident ahead of the consumer via ``jax.device_put``; ``pipelined=True``
  moves production+placement onto a put thread so batch k+1's transfer
  overlaps batch k's dispatch (``step.infeed.wait`` vs ``step.infeed.put``
  spans).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Iterator, Sequence

import numpy as np

from shifu_tensorflow_tpu.data.reader import (
    ParsedBlock,
    RecordSchema,
    parse_buffer_split,
)
from shifu_tensorflow_tpu.utils import fs

Batch = dict[str, np.ndarray]  # {"x": (B,F), "y": (B,1), "w": (B,1)}


def resolve_stream_feature_dtype(setting: str | None, *,
                                 uses_feature_hashing: bool,
                                 has_normalization_stats: bool = True) -> str:
    """Streaming TRANSPORT dtype for features (conf key
    shifu.tpu.stream-feature-dtype), decoupled from the compute dtype.

    ``auto`` (the default) ships bf16 whenever it is safe: half the cache
    slab bytes and 4.6× the fp32 host→device rate measured through the
    tunneled backend (BENCH_TRANSFER.json); the jitted step widens back to
    the params' precision on device (train/trainer.py _widen_features), so
    an fp32 model still computes fp32 — bf16 is transport-only.

    Two unsafe cases keep ``auto`` at float32:

    - models that HASH feature columns (embedding / wide-cross): bucket
      ids are computed from raw float bits; bf16 rounding of category
      codes > 256 would re-bucket them, skewing training against the
      f32-hashing exported scorer.  An explicit bfloat16 request refuses
      loudly rather than silently skewing;
    - no ZSCALE normalization stats (``has_normalization_stats=False``):
      z-scaled features are O(1) where bf16's 8-bit mantissa is plenty,
      but RAW features (un-normalized numeric codes, large-magnitude
      amounts fed densely) lose low-order digits with no warning — the
      KS-parity evidence behind the bf16 default only covers normalized
      pipelines.  An explicit ``bfloat16`` still forces it (the operator
      owns the precision claim); ``auto`` stays conservative.
    """
    s = (setting or "auto").lower()
    if s == "auto":
        if uses_feature_hashing or not has_normalization_stats:
            return "float32"
        return "bfloat16"
    if s == "bfloat16" and uses_feature_hashing:
        raise ValueError(
            "shifu.tpu.stream-feature-dtype=bfloat16 is unsafe with "
            "hashed feature columns: bucket ids are computed from raw "
            "float bits, and bf16 rounding re-buckets category codes "
            "> 256 — use auto (streams float32 for hashing models)"
        )
    if s not in ("float32", "bfloat16"):
        raise ValueError(
            f"unknown stream-feature-dtype {setting!r} "
            "(auto | float32 | bfloat16)"
        )
    return s

def make_batch(x: np.ndarray, y: np.ndarray, w: np.ndarray) -> Batch:
    return {"x": x, "y": y, "w": w}


def pad_to_batch(block: ParsedBlock, batch_size: int) -> ParsedBlock:
    """Zero-pad rows to a multiple of batch_size with weight=0 rows."""
    n = len(block)
    rem = n % batch_size
    if rem == 0 and n > 0:
        return block
    pad = batch_size - rem if n > 0 else batch_size
    # padding keeps the block's feature dtype: a float32 pad concatenated
    # onto bfloat16 features would silently promote the whole batch
    f = np.zeros((pad, block.features.shape[1]), block.features.dtype)
    z = np.zeros((pad, 1), np.float32)
    return ParsedBlock.concat([block, ParsedBlock(f, z, z)])


def iter_batches(block: ParsedBlock, batch_size: int, *, shuffle: bool = False,
                 seed: int = 0) -> Iterator[Batch]:
    """Slice an in-memory block into fixed-size batches."""
    if len(block) == 0:
        return
    if shuffle:
        perm = np.random.default_rng(seed).permutation(len(block))
        block = ParsedBlock(
            block.features[perm], block.targets[perm], block.weights[perm]
        )
    padded = pad_to_batch(block, batch_size)
    for i in range(0, len(padded), batch_size):
        sl = slice(i, i + batch_size)
        yield make_batch(padded.features[sl], padded.targets[sl], padded.weights[sl])


@dataclass
class InMemoryDataset:
    """Fully-loaded shard with deterministic train/valid split — the
    reference ``load_data`` contract (ssgd_monitor.py:348-454) for datasets
    that fit in host RAM (the demo / unit-test path)."""

    train: ParsedBlock
    valid: ParsedBlock
    schema: RecordSchema

    @classmethod
    def load(
        cls,
        paths: Sequence[str],
        schema: RecordSchema,
        valid_rate: float,
        salt: int = 0,
    ) -> "InMemoryDataset":
        train_blocks, valid_blocks = [], []
        for path in paths:
            with fs.open_maybe_gzip(path) as f:
                buf = f.read()
            tr, va = parse_buffer_split(buf, schema, valid_rate, salt)
            train_blocks.append(tr)
            valid_blocks.append(va)
        if not train_blocks:
            empty = ParsedBlock.empty(schema.num_features)
            return cls(empty, empty, schema)
        return cls(
            ParsedBlock.concat(train_blocks),
            ParsedBlock.concat(valid_blocks),
            schema,
        )

    def train_batches(self, batch_size: int, *, epoch: int = 0) -> Iterator[Batch]:
        return iter_batches(self.train, batch_size, shuffle=True, seed=epoch)

    def valid_batches(self, batch_size: int) -> Iterator[Batch]:
        return iter_batches(self.valid, batch_size)

    def train_batches_fixed(
        self, batch_size: int, steps: int, *, epoch: int = 0
    ) -> Iterator[Batch]:
        """Exactly ``steps`` batches (zero-weight padded) — SPMD epochs."""
        return fixed_step_batches(
            iter_batches(self.train, batch_size, shuffle=True, seed=epoch),
            batch_size, steps, self.schema.num_features,
        )

    def valid_batches_fixed(self, batch_size: int, steps: int) -> Iterator[Batch]:
        return fixed_step_batches(
            iter_batches(self.valid, batch_size),
            batch_size, steps, self.schema.num_features,
        )

    def steps_per_epoch(self, batch_size: int) -> int:
        return -(-len(self.train) // batch_size)

    def valid_steps(self, batch_size: int) -> int:
        return -(-len(self.valid) // batch_size)


def _zero_batch(batch_size: int, num_features: int,
                x_dtype=np.float32) -> Batch:
    """All-padding batch: weight 0 everywhere, so it contributes nothing to
    the weighted loss/gradient — pure barrier participation.  ``x_dtype``
    must match the real batches' feature dtype or SPMD processes would
    compile different programs for padded vs real steps."""
    z = np.zeros((batch_size, 1), np.float32)
    return make_batch(np.zeros((batch_size, num_features), x_dtype), z, z)


def fixed_step_batches(
    batches: Iterable[Batch],
    batch_size: int,
    steps: int,
    num_features: int,
    *,
    on_dropped: Callable[[int], None] | None = None,
    x_dtype=np.float32,
) -> Iterator[Batch]:
    """Adapt any batch iterator to EXACTLY ``steps`` batches of exactly
    ``batch_size`` rows.

    Under cross-process SPMD every process must execute the same number of
    identically-shaped steps per epoch or the collective deadlocks (XLA
    all-reduce is a barrier; the reference had the same constraint spread
    across SyncReplicasOptimizer's token queue, ssgd_monitor.py:136-142).
    Shards are rarely equal-sized, so the coordinator agrees on the MAX step
    count and shorter shards pad with zero-weight batches; a source yielding
    more than ``steps`` batches has the surplus dropped (``on_dropped``
    receives the dropped row count — callers log it; silent truncation reads
    as full coverage when it isn't).

    Returns a closeable iterator that remembers ``batches`` as its ROOT:
    ``close()`` closes the root stream FIRST (object-level, thread-safe —
    it can unwedge a pipelined-infeed put thread blocked inside this
    adapter's generator, whose own close() is refused while its frame is
    live on that thread) and then the generator.
    """
    return _RootedBatches(
        _fixed_step_gen(batches, batch_size, steps, num_features,
                        on_dropped=on_dropped, x_dtype=x_dtype),
        batches,
    )


class _RootedBatches:
    """A generator chain paired with the root stream object under it.

    Iterating delegates to the generator.  ``close()`` goes root-first:
    the root's object-level close is safe from any thread and releases
    the producer machinery (ShardStream contract), after which closing
    the generator itself (running its ``finally``) succeeds once no
    thread is executing its frame."""

    __slots__ = ("_gen", "root")

    def __init__(self, gen, root):
        self._gen = gen
        self.root = root

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self) -> None:
        close_stream(self.root)
        close_stream(self._gen)


def _fixed_step_gen(
    batches: Iterable[Batch],
    batch_size: int,
    steps: int,
    num_features: int,
    *,
    on_dropped: Callable[[int], None] | None = None,
    x_dtype=np.float32,
) -> Iterator[Batch]:
    it = iter(batches)
    try:
        emitted = 0
        for batch in it:
            if emitted >= steps:
                dropped = int(batch["x"].shape[0])
                for extra in it:
                    dropped += int(extra["x"].shape[0])
                if on_dropped is not None and dropped:
                    on_dropped(dropped)
                return
            n = batch["x"].shape[0]
            if n != batch_size:  # pad a short (final) batch to the fixed shape
                pad = batch_size - n
                batch = {
                    k: np.concatenate(
                        [np.asarray(v), np.zeros((pad,) + v.shape[1:], v.dtype)]
                    )
                    for k, v in batch.items()
                }
            yield batch
            emitted += 1
        while emitted < steps:
            yield _zero_batch(batch_size, num_features, x_dtype)
            emitted += 1
    finally:
        # close-through: abandoning this adapter (step cap reached, caller
        # exception, rollback) must release the wrapped stream's producer
        # threads — the ShardStream close() contract
        close_stream(batches)


def close_stream(obj) -> None:
    """Close a batch source if it supports it (ShardStream, a generator,
    a pipelined prefetcher); quietly ignore sources that don't.  The one
    teardown helper every epoch path's ``finally`` uses.

    A generator whose frame is LIVE on another thread (a pipelined-infeed
    put thread blocked mid-``next()``) refuses ``close()`` with
    ValueError("generator already executing") — swallowed here: the
    abandonment paths close the ROOT stream too, whose stop signal is
    what actually releases that thread, and letting the ValueError fly
    out of an epoch ``finally`` would mask the original exception."""
    close = getattr(obj, "close", None)
    if callable(close):
        try:
            close()
        except ValueError as e:
            if "already executing" not in str(e):
                raise


class ShardStream:
    """Streaming reader: files → staged pull pipeline → fixed batches.

    A thin facade over ``data/pipeline.ShardPipeline`` — parallel shard
    readers (static round-robin shard→reader assignment), a decode/cast
    pool, an order-preserving pull sequencer, an optional seeded shuffle
    buffer, and fixed-shape batch formation.  Each file is served from the
    fastest available source, in order:

    1. **binary cache hit** (``cache_dir`` set, entry valid): finalized
       tensors are memory-mapped and batches are zero-copy views — ingest
       at page-cache speed, the steady-state multi-epoch path
       (data/cache.py);
    2. **fused native stream** (local file, native lib built): one C++ pass
       does read→inflate→parse (cpp/stpu_data.cc stpu_stream_*) with the
       GIL released; a cache entry is written as a side effect when
       ``cache_dir`` is set;
    3. **byte-chunk fallback** (remote schemes / no native lib): fs-layer
       reads, parsed in the decode pool.

    Determinism: the emitted batch sequence is a pure function of
    (path order, schema, salt, batch size, shuffle knobs) — INDEPENDENT of
    ``n_readers``, ``decode_workers``, queue depths, and thread
    interleaving (the sequencer merges per-reader queues in global shard
    order).  Parallel ingest is therefore safe to enable — and to
    autotune — without losing reproducibility; a fixed seed plus a fixed
    shard list replays the identical epoch (tests/test_ingest.py pins
    this across 1/2/4 readers and across chaos-drill resumes).

    Lifecycle: iterating to completion releases every pipeline thread; an
    abandoned iterator is released by ``close()`` (also available as a
    context manager), which the trainer's epoch paths call from their
    ``finally`` blocks.
    """

    def __init__(
        self,
        paths: Sequence[str],
        schema: RecordSchema,
        batch_size: int,
        *,
        valid_rate: float = 0.0,
        emit: str = "train",  # which side of the split to emit
        block_bytes: int = 4 << 20,
        block_rows: int = 1 << 16,
        queue_depth: int = 4,
        drop_remainder: bool = False,
        salt: int = 0,
        n_readers: int | None = None,
        cache_dir: str | None = None,
        feature_dtype: str = "float32",
        decode_workers: int | None = None,
        shuffle_rows: int = 0,
        shuffle_seed: int | None = None,
        retry_policy=None,
        stats_sink: "Callable | None" = None,
        traced: bool | None = None,
    ):
        self.paths = list(paths)
        self.schema = schema
        self.batch_size = batch_size
        self.valid_rate = valid_rate
        self.emit = emit
        self.block_bytes = block_bytes
        self.block_rows = block_rows  # native fused-stream rows per chunk
        # per-reader chunk-queue capacity: bounds read-ahead AND in-flight
        # decodes (futures live in the queue)
        self.queue_depth = queue_depth
        self.drop_remainder = drop_remainder
        self.salt = salt
        self.cache_dir = cache_dir
        # "float32" | "bfloat16": emitted batch x dtype; bf16 halves cache
        # slab reads and host->device transfer for bf16 training runs
        self.feature_dtype = feature_dtype or "float32"
        if n_readers is None:
            n_readers = 1
        self.n_readers = max(1, min(n_readers, max(1, len(self.paths))))
        self.decode_workers = max(1, decode_workers or 1)
        self.shuffle_rows = max(0, int(shuffle_rows))
        self.shuffle_seed = salt if shuffle_seed is None else int(shuffle_seed)
        self.retry_policy = retry_policy
        # called with the epoch's StageStats after each full iteration /
        # close — the autotuner's feedback channel (data/autotune.py)
        self.stats_sink = stats_sink
        # record ingest.* spans to the installed tracer?  None = auto:
        # train-side streams trace, valid-side streams don't — the eval
        # pass runs untraced by discipline (trainer.evaluate), and its
        # ingest work polluting the train epoch's journaled span budget
        # would point the hand-tuning decision table (docs/ingest.md) at
        # the wrong stage
        self.traced = (emit != "valid") if traced is None else bool(traced)
        self._live: list = []  # pipelines with threads possibly running

    def close(self) -> None:
        """Release every live pipeline (producer threads, decode pool,
        uncommitted cache writers).  Idempotent; the contract every
        consumer that may abandon the iterator mid-epoch must honor."""
        for pipe in list(self._live):
            pipe.close()

    def __enter__(self) -> "ShardStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __iter__(self) -> Iterator[Batch]:
        from shifu_tensorflow_tpu.data.pipeline import (
            ShardPipeline,
            StageStats,
            blocks_to_batches,
            route_blocks,
            shuffled_blocks,
        )

        from shifu_tensorflow_tpu.obs import datastats as obs_datastats
        from shifu_tensorflow_tpu.obs import trace as obs_trace

        stats = StageStats()
        tracer = obs_trace.active() if self.traced else None
        # data-observability tap (obs/datastats.py): TRAIN-emit streams
        # only — the exported feature baseline must describe what the
        # model trained on, not the validation split's reweighted view
        # (same per-emit discipline as the tracer above)
        stats_tap = (obs_datastats.train_active()
                     if self.emit != "valid" else None)
        pipe = ShardPipeline(
            self.paths, self.schema,
            salt=self.salt,
            n_readers=self.n_readers,
            decode_workers=self.decode_workers,
            queue_depth=self.queue_depth,
            block_bytes=self.block_bytes,
            block_rows=self.block_rows,
            cache_dir=self.cache_dir,
            feature_dtype=self.feature_dtype,
            need_hashes=self.valid_rate > 0.0,
            retry_policy=self.retry_policy,
            stats=stats,
            tracer=tracer,
        )
        self._live.append(pipe)
        try:
            routed = route_blocks(
                pipe.blocks(), emit=self.emit, valid_rate=self.valid_rate,
            )
            blocks = shuffled_blocks(routed, self.shuffle_rows,
                                     self.shuffle_seed, stats,
                                     tracer=tracer)
            yield from blocks_to_batches(
                blocks, self.batch_size, self.schema.num_features,
                drop_remainder=self.drop_remainder,
                stats_tap=stats_tap,
            )
        finally:
            pipe.close()
            if pipe in self._live:
                self._live.remove(pipe)
            if self.stats_sink is not None:
                try:
                    self.stats_sink(stats)
                except Exception:  # a broken sink must not kill training
                    pass


def prefetch_to_device(
    batches: Iterable[Batch],
    put: Callable[[Batch], Batch] | None = None,
    depth: int = 2,
    *,
    pipelined: bool = False,
    tracer=None,
    root=None,
):
    """Keep ``depth`` batches already transferred ahead of the consumer.

    ``put`` maps a host batch to device (default ``jax.device_put``); with a
    ``NamedSharding`` it lands shards directly on the mesh.

    Two modes:

    - **unthreaded** (default): a plain generator — ``put`` runs inline in
      the consumer thread while filling the deque, so placement time is
      consumer-visible.  The host-embedding path DEPENDS on this (its
      zero-staleness contract needs gather→update ordering in one thread —
      trainer._train_epoch_host_emb).
    - **pipelined** (``pipelined=True``): a producer thread runs
      ``next(batches)`` + ``put`` and feeds a bounded queue, so host batch
      production AND device placement of batch k+1 overlap the dispatch of
      batch k — the double-buffered infeed stage of the ingest pipeline
      (docs/ingest.md).  The consumer's only stall is the queue wait.
      Span split: ``step.infeed.put`` (thread-side placement work) vs
      ``step.infeed.wait`` (consumer-side starvation) — ``obs summary``
      uses it to distinguish "starved" from "placement-slow".

    The returned object supports ``close()`` (no-op for the unthreaded
    generator beyond normal generator close) — epoch paths close it in
    ``finally`` so an abandoned epoch never leaks the put thread.

    ``root`` (pipelined mode only) is the epoch's ROOT stream object
    (e.g. the ShardStream) when ``batches`` is a generator chain over
    it.  ``close()`` closes the root FIRST: object-level closes are
    thread-safe, and signalling the underlying pipeline's stop event is
    the only thing that can unwedge a put thread blocked inside
    ``next()`` on a stalled stream — a generator whose frame is live on
    the put thread refuses ``close()`` outright (ValueError).
    """
    if pipelined:
        return _PipelinedPrefetch(batches, put, depth, tracer, root=root)
    return _sync_prefetch(batches, put, depth)


def _sync_prefetch(
    batches: Iterable[Batch],
    put: Callable[[Batch], Batch] | None,
    depth: int,
) -> Iterator[Batch]:
    import collections

    import jax

    if put is None:
        put = jax.device_put

    buf: "collections.deque" = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(buf) < depth:
                buf.append(put(next(it)))
            yield buf.popleft()
    except StopIteration:
        while buf:
            yield buf.popleft()


class _PipelinedPrefetch:
    """Threaded device-put stage: one producer thread pulls host batches,
    places them, and fills a bounded queue the consumer drains.

    Order-preserving (single thread, FIFO queue).  Errors from the source
    iterator or from ``put`` re-raise in the consumer.  ``close()`` stops
    the thread, drains the queue, joins, then closes the source — safe to
    call from the consumer's ``finally`` at any point mid-epoch.
    """

    _END = object()

    #: close() abandons the put thread past this deadline instead of
    #: hanging the caller; with a root stream attached the thread always
    #: unwedges well inside it (the root's stop signal propagates in
    #: ≤ one queue-poll interval), so this is a backstop, not a budget
    _JOIN_TIMEOUT_S = 10.0

    def __init__(self, batches, put, depth, tracer=None, root=None):
        import jax

        self._src = batches
        self._root = root
        put_fn = put if put is not None else jax.device_put
        # only the EXPLICIT tracer records (no fallback to the process
        # install): the eval pass runs untraced on purpose — its waits
        # must not inflate the train epoch's step budget.  Recording goes
        # through the tracer's SAMPLED seams because budget_fields scales
        # step.* spans back up by sample_every — an unsampled side
        # channel would overcount under obs-trace-sample > 1.
        self._tracer = tracer
        self._put_fn = (
            tracer.timed("step.infeed.put", put_fn)
            if tracer is not None else put_fn
        )
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="stpu-infeed-put", daemon=True
        )
        self._closed = False
        self._thread.start()

    # ---- producer ----
    def _run(self) -> None:
        try:
            it = iter(self._src)
            while not self._stop.is_set():
                try:
                    b = next(it)
                except StopIteration:
                    break
                d = self._put_fn(b)
                if not self._enqueue(d):
                    return
            self._enqueue(self._END)
        except BaseException as e:
            self._enqueue(_PrefetchError(e))

    def _enqueue(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # ---- consumer ----
    def __iter__(self) -> Iterator[Batch]:
        from shifu_tensorflow_tpu.obs import trace as obs_trace

        while True:
            with obs_trace.maybe_span(self._tracer, "step.infeed.wait"):
                item = self._dequeue()
            if item is self._END:
                return
            if isinstance(item, _PrefetchError):
                raise item.exc
            yield item

    def _dequeue(self):
        while True:
            try:
                return self._q.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive():
                    # thread died without a terminal marker (should be
                    # unreachable — _run always posts one) — fail loudly
                    # rather than hang the epoch
                    raise RuntimeError("infeed put thread died silently")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # unwedge the put thread FIRST: if it is blocked inside next() on
        # a stream whose producers stalled, only the source's own stop
        # signal releases it — this prefetcher's stop event is checked
        # only between batches.  The root's close() is object-level and
        # thread-safe (close_stream itself tolerates a generator root
        # whose frame is live on the put thread).
        close_stream(self._root)
        deadline = time.monotonic() + self._JOIN_TIMEOUT_S
        while self._thread.is_alive():
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)
            if self._thread.is_alive() and time.monotonic() > deadline:
                break  # daemon thread; exits once its blocked call returns
        # the source is no longer being consumed; release ITS threads and
        # run the generator chain's finallys (stats sink, pipeline close).
        # Safe now that the thread is joined (frames suspended); in the
        # abandoned-thread case a live frame refuses close and
        # close_stream swallows it.
        close_stream(self._src)


class _PrefetchError:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc
