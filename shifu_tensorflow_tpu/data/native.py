"""ctypes bindings for the native block parser (cpp/stpu_data.cc).

``parse_buffer`` is the fast path under ``reader.parse_buffer_split``: one
call parses a multi-megabyte block of decompressed shard bytes into float32
arrays plus per-row crc32 routing hashes, with the GIL released — the
Python fallback does the same work row-by-row in the interpreter.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from shifu_tensorflow_tpu import _native

_lib = None
_checked = False


def _load():
    global _lib, _checked
    if not _checked:
        lib = _native.load("stpu_data")
        if lib is not None:
            try:
                lib.stpu_parse_buffer.restype = ctypes.c_long
                lib.stpu_parse_buffer.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_long,
                    ctypes.c_char,
                    ctypes.POINTER(ctypes.c_int),
                    ctypes.c_int,
                    ctypes.c_uint,
                    ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_uint),
                    ctypes.c_long,
                    ctypes.c_int,
                    ctypes.c_long,
                ]
                lib.stpu_count_lines.restype = ctypes.c_long
                lib.stpu_count_lines.argtypes = [ctypes.c_char_p, ctypes.c_long]
                lib.stpu_stream_open.restype = ctypes.c_void_p
                lib.stpu_stream_open.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char,
                    ctypes.POINTER(ctypes.c_int),
                    ctypes.c_int,
                    ctypes.c_uint,
                    ctypes.c_int,
                ]
                lib.stpu_stream_next.restype = ctypes.c_long
                lib.stpu_stream_next.argtypes = [
                    ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_uint),
                    ctypes.c_long,
                ]
                lib.stpu_stream_error.restype = ctypes.c_char_p
                lib.stpu_stream_error.argtypes = [ctypes.c_void_p]
                lib.stpu_stream_close.restype = None
                lib.stpu_stream_close.argtypes = [ctypes.c_void_p]
            except AttributeError:
                lib = None
        _lib = lib
        _checked = True
    return _lib


def available() -> bool:
    return _load() is not None


def parse_buffer(
    buf: bytes,
    wanted_columns: tuple[int, ...],
    delimiter: str,
    *,
    salt: int = 0,
    want_hashes: bool = True,
    n_threads: int | None = None,
) -> "tuple[np.ndarray, np.ndarray | None] | None":
    """Parse delimited text ``buf`` into ``(rows x len(wanted_columns))``
    float32 plus per-row routing hashes.  Returns None when the native
    library is unavailable or declines (e.g. duplicate wanted columns) —
    caller falls back to Python."""
    lib = _load()
    # the byte length is what matters: a non-ASCII delimiter like '¦' is one
    # str char but multiple UTF-8 bytes — splitting on its lead byte would
    # silently diverge from the Python path
    delim = delimiter.encode()
    if lib is None or len(delim) != 1:
        return None
    if n_threads is None:
        n_threads = min(8, os.cpu_count() or 1)

    n_wanted = len(wanted_columns)
    cap = int(lib.stpu_count_lines(buf, len(buf)))
    if cap == 0:
        out = np.empty((0, n_wanted), np.float32)
        return out, (np.empty((0,), np.uint32) if want_hashes else None)

    out = np.empty((cap, n_wanted), np.float32)
    hashes = np.empty((cap,), np.uint32) if want_hashes else None
    cols = (ctypes.c_int * n_wanted)(*wanted_columns)
    n = lib.stpu_parse_buffer(
        buf,
        len(buf),
        delim,
        cols,
        n_wanted,
        ctypes.c_uint(salt & 0xFFFFFFFF),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        (
            hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint))
            if hashes is not None
            else None
        ),
        cap,
        n_threads,
        cap,  # line count already computed above; skips the recount
    )
    if n < 0:
        return None
    return out[:n], (hashes[:n] if hashes is not None else None)


def stream_blocks(
    path: str,
    wanted_columns: tuple[int, ...],
    delimiter: str,
    *,
    salt: int = 0,
    want_hashes: bool = True,
    block_rows: int = 1 << 16,
):
    """Generator over ``(arr, hashes)`` blocks of a delimited shard, parsed
    by the fused native read→inflate→parse stream (cpp/stpu_data.cc
    stpu_stream_*).  Yields fresh arrays (the consumer keeps references).
    Returns None (instead of a generator) when the native path is
    unavailable — caller falls back to the Python byte-chunk path.
    """
    lib = _load()
    delim = delimiter.encode()
    if lib is None or len(delim) != 1:
        return None
    n_wanted = len(wanted_columns)
    cols = (ctypes.c_int * n_wanted)(*wanted_columns)
    handle = lib.stpu_stream_open(
        os.fsencode(path), delim, cols, n_wanted,
        ctypes.c_uint(salt & 0xFFFFFFFF), 1 if want_hashes else 0,
    )
    if not handle:
        return None

    def _gen():
        try:
            while True:
                out = np.empty((block_rows, n_wanted), np.float32)
                hashes = np.empty((block_rows,), np.uint32) if want_hashes else None
                n = lib.stpu_stream_next(
                    handle,
                    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    (
                        hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint))
                        if hashes is not None
                        else None
                    ),
                    block_rows,
                )
                if n < 0:
                    msg = lib.stpu_stream_error(handle)
                    raise OSError(
                        f"native stream failed on {path}: "
                        f"{(msg or b'?').decode(errors='replace')}"
                    )
                if n == 0:
                    return
                yield out[:n], (hashes[:n] if hashes is not None else None)
        finally:
            lib.stpu_stream_close(handle)

    return _gen()
