"""Training-data sharding across workers.

Parity surface: the reference's ``TrainingDataSet`` lists HDFS files
recursively under the training path, skips hidden (``.``/``_``) files, and
round-robins file paths across workers, throwing when there are fewer files
than workers (reference: TrainingDataSet.java:55-89).  Its own TODO asks for
a size-aware upgrade (:32-34) — implemented here as the default strategy:
greedy largest-first assignment to the currently-lightest worker, which
bounds shard skew instead of hoping file sizes are uniform.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

from shifu_tensorflow_tpu.utils import fs


class NotEnoughFilesError(ValueError):
    """Fewer data files than workers (parity: TrainingDataSet.java:84-86)."""


def list_data_files(training_data_path: str) -> list[str]:
    """Recursively list data files, skipping ``.``/``_`` prefixed names
    (Hadoop hidden/success markers), sorted for determinism."""
    out = []
    for p in fs.listdir_recursive(training_data_path):
        base = p.rsplit("/", 1)[-1]
        if base.startswith(".") or base.startswith("_"):
            continue
        out.append(p)
    return sorted(out)


@dataclass(frozen=True)
class Shard:
    worker_index: int
    paths: tuple[str, ...]
    total_bytes: int

    def joined(self) -> str:
        """Comma-joined path string — the reference's wire format for the
        TRAINING_DATA_PATH env var (TensorflowTask.java:148-162)."""
        return ",".join(self.paths)


def split_round_robin(paths: list[str], num_workers: int) -> list[Shard]:
    """Straight round-robin by listing order (reference behavior,
    TrainingDataSet.java:66-82)."""
    _check(paths, num_workers)
    buckets: list[list[str]] = [[] for _ in range(num_workers)]
    for i, p in enumerate(paths):
        buckets[i % num_workers].append(p)
    return [
        Shard(w, tuple(b), sum(_size_safe(p) for p in b))
        for w, b in enumerate(buckets)
    ]


def split_size_aware(
    paths: list[str], num_workers: int,
    sizes: dict[str, int] | None = None,
) -> list[Shard]:
    """Greedy LPT: largest file first onto the lightest worker — the upgrade
    the reference's TODO requests (TrainingDataSet.java:32-34).

    ``sizes`` lets a caller supply pre-gathered byte sizes (falling back
    to a live stat per missing path) — the coordinator's elastic
    re-split runs under its serving lock, and one stat per data file on
    a slow filesystem there would stall heartbeats long enough to expire
    healthy workers mid-recovery."""
    _check(paths, num_workers)
    if sizes is None:
        sizes = {}
    sized = sorted(
        ((sizes[p] if p in sizes else _size_safe(p), p) for p in paths),
        reverse=True,
    )
    heap: list[tuple[int, int]] = [(0, w) for w in range(num_workers)]
    heapq.heapify(heap)
    buckets: list[list[str]] = [[] for _ in range(num_workers)]
    loads = [0] * num_workers
    for size, p in sized:
        load, w = heapq.heappop(heap)
        buckets[w].append(p)
        loads[w] = load + size
        heapq.heappush(heap, (loads[w], w))
    return [Shard(w, tuple(buckets[w]), loads[w]) for w in range(num_workers)]


def split_training_data(
    training_data_path: str, num_workers: int, strategy: str = "size_aware"
) -> list[Shard]:
    paths = list_data_files(training_data_path)
    if strategy == "round_robin":
        return split_round_robin(paths, num_workers)
    return split_size_aware(paths, num_workers)


def total_line_count(paths: list[str]) -> int:
    """Sum of per-file line counts — TOTAL_TRAINING_DATA_NUMBER parity
    (HdfsUtils.getFileLineCount, HdfsUtils.java:143-175)."""
    return sum(fs.count_lines(p) for p in paths)


def _check(paths: list[str], num_workers: int) -> None:
    if num_workers <= 0:
        raise ValueError(f"num_workers must be positive, got {num_workers}")
    if len(paths) < num_workers:
        raise NotEnoughFilesError(
            f"{len(paths)} data file(s) for {num_workers} workers; "
            "need at least one file per worker"
        )


def _size_safe(path: str) -> int:
    # floor of 1 so zero-byte/unstatable files still carry weight in LPT;
    # otherwise all ties pile onto worker 0 and other workers get empty
    # shards, the exact condition NotEnoughFilesError exists to prevent
    try:
        return max(fs.size(path), 1)
    except OSError:
        return 1
