"""Job coordinator — the control plane.

Replaces the reference's L1-L3 stack: YARN client + ApplicationMaster +
embedded ZooKeeper (TensorflowClient.java, TensorflowApplicationMaster.java,
TensorflowSession.java) with one process owning worker registration, shard
assignment, the start barrier, liveness, metrics aggregation, and the
failure policy.  The znode contract (/tensorflow_cluster/<id>, /final,
backup wake-up, /worker_intermediate_result — Constants.java:72-80) becomes
a newline-delimited-JSON TCP protocol served here.

Design translations (SURVEY.md §7.0):
- partial-cluster start (95% + 6-min compaction) → **wait-for-all with
  timeout → abort**: SPMD needs every participant, so the coordinator
  barriers all workers with a hard registration deadline instead of
  compacting a partial cluster;
- backup hot-swap (weakupBackup, TensorflowSession.java:748-781) →
  **checkpoint-restart**: a failed worker is relaunched by the submitter
  and resumes from the latest sharded checkpoint (its shard assignment is
  sticky by worker_id);
- chief short-circuit (TensorflowSession.java:434-452): worker 0 failing
  permanently fails the job;
- fault tolerance envelope: at most ``floor(0.1 * n_workers) + spares``
  worker restarts (Constants.java:87-89) before the job fails.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from enum import Enum
from typing import Any

from shifu_tensorflow_tpu.config import keys as K
from shifu_tensorflow_tpu.coordinator.heartbeat import LivenessMonitor
from shifu_tensorflow_tpu.coordinator.metrics_board import EpochAggregator
from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.obs import trace as obs_trace
from shifu_tensorflow_tpu.obs.registry import MetricsRegistry
from shifu_tensorflow_tpu.parallel.mesh import mesh_coord, parse_mesh_shape
from shifu_tensorflow_tpu.train.trainer import EpochStats
from shifu_tensorflow_tpu.utils import faults, logs
from shifu_tensorflow_tpu.utils import retry as retry_util

log = logs.get("coordinator")

#: addresses peers cannot reach across machines; shared with the submitter
LOOPBACK_HOSTS = ("", "127.0.0.1", "localhost", "::1")


class JobState(str, Enum):
    """SessionState parity (TensorflowSession.java:837-839) plus terminal
    success/failure."""

    REGISTERING = "registering"
    TRAINING = "training"
    FINISHED = "finished"
    FAILED = "failed"


@dataclass
class WorkerRecord:
    worker_id: str
    worker_index: int
    shard_paths: tuple[str, ...] = ()
    registered_at: float = 0.0
    completed: bool = False
    exit_code: int | None = None
    restarts: int = 0
    # "worker" | "standby": standbys hold no rank (worker_index -1) and
    # live in Coordinator.standbys until promoted into a dead rank
    role: str = "worker"
    # cross-process SPMD bring-up: the worker's host and, for the chief, the
    # TCP port it reserved for the jax coordination service
    host: str = ""
    jax_port: int = 0
    # which fleet generation this record last registered into (SPMD
    # recovery restarts the whole fleet; see _fleet_restart)
    generation: int = 0
    # non-SPMD health rollback state, scoped to THIS worker: independent
    # models roll back independently, so worker B's LR back-off and skip
    # window must never leak into worker C's relaunch (SPMD uses the
    # coordinator-level fleet directive instead — one model, one policy)
    lr_scale: float = 1.0
    skip_directive: dict | None = None


#: cooperative exit code for a worker leaving because the fleet is
#: restarting (not a failure; does not consume restart budget)
RESTART_EXIT_CODE = 44

#: sliding window for the restart-budget burn gauge (matches the serve
#: supervisor's crash-restart window, serve/__main__.py)
RESTART_BURN_WINDOW_S = 600.0

#: cooperative exit code for a worker leaving after its health guard
#: tripped and the coordinator granted a rollback: the BUDGET was already
#: charged by report_unhealthy, so complete() must not charge it again —
#: but the worker is restartable (it resumes from the last verified
#: checkpoint with the rollback directive applied)
UNHEALTHY_EXIT_CODE = 45


@dataclass
class JobSpec:
    n_workers: int
    shards: list  # list[Shard] from data.splitter (index-aligned to workers)
    total_rows: int = 0
    epochs: int = 1
    # fleet-wide correlation id stamped on every journal event and
    # handed to workers in the register reply ("" = the coordinator
    # mints one): one merged journal can then tell two jobs apart and
    # `obs trace` can scope a query to one job's causal story
    job_id: str = ""
    registration_timeout_s: float = K.REGISTRATION_HARD_TIMEOUT_S
    max_worker_failure_ratio: float = K.WORKER_FAULT_TOLERANCE_THRESHOLD
    spare_restarts: int = 0  # analogue of backup instances
    heartbeat_interval_ms: int = K.DEFAULT_TASK_HEARTBEAT_INTERVAL_MS
    max_missed_heartbeats: int = K.DEFAULT_TASK_MAX_MISSED_HEARTBEATS
    board_path: str | None = None
    # epoch synchronization: workers barrier after each epoch until every
    # worker has reported it — the coordinator-level analogue of the
    # reference's SyncReplicasOptimizer per-step synchronization
    # (ssgd_monitor.py:136-142); a dead worker holds the barrier until its
    # relaunch catches up, so recovery is deterministic, not racy
    sync_epochs: bool = False
    epoch_barrier_timeout_s: float = 300.0
    # cross-process SPMD: the worker fleet is ONE jax.distributed job
    # training one model (gradient all-reduce across processes — the
    # SyncReplicasOptimizer semantic, ssgd_monitor.py:136-142).  Any worker
    # failure restarts the whole fleet from the shared checkpoint, because
    # SPMD cannot lose a participant mid-collective (SURVEY.md §2.5).
    spmd: bool = False
    # per-shard line counts, index-aligned to ``shards`` (None = unknown;
    # workers count their own shard once and the coordinator caches it so
    # fleet restarts never re-read 1B-row shards just to size their epochs)
    shard_lines: list | None = None
    # fleet-coordinated early stopping (shifu.tpu.early-stop-*): evaluated
    # by the COORDINATOR on full-quorum epoch aggregates (mean shard-local
    # KS / valid loss across workers) and delivered through the epoch
    # barrier, so every worker stops after the SAME epoch — an
    # uncoordinated per-worker stop would hang SPMD collectives.  Enabling
    # either forces sync_epochs.
    early_stop_ks: float = 0.0
    early_stop_patience: int = 0
    # training-health rollback policy (shifu.tpu.health-*): a worker whose
    # health guard trips (NaN/Inf loss or grad, loss spike, hung step)
    # reports `unhealthy`; the coordinator arbitrates ONE fleet-wide
    # rollback — restore the last verified checkpoint, scale the learning
    # rate by health_lr_backoff, and skip the offending batch window.
    # Rollbacks are charged against the SAME restart budget as crashes
    # (spare_restarts et al.) AND capped by health_max_rollbacks; either
    # limit exhausted fails the job fast with a diagnostic bundle.
    health_lr_backoff: float = 0.5
    health_max_rollbacks: int = 2
    # skip window width: each reported bad step plus (window - 1) steps
    # before it is skipped on the replay (trailing steps are covered by
    # the report itself — the guard lists every non-finite step)
    health_skip_window: int = 1
    # elastic fleet (shifu.tpu.standby-workers / shifu.tpu.elastic).
    # standby_workers: hot standbys launched beside the fleet; they
    # register with role=standby, pre-build their model (compile warm, no
    # shard), heartbeat like any worker, and on a rank failure the
    # coordinator PROMOTES the freshest-heartbeat standby into the dead
    # rank — same index, same shard, current generation — instead of
    # charging the restart budget (non-SPMD: the surviving ranks never
    # roll back; SPMD: the standby substitutes into an UNCHARGED fleet
    # restart, resuming from the latest verified epoch).
    standby_workers: int = 0
    # elastic=True: a rank failure with no standby left AND the restart
    # budget exhausted SHRINKS the fleet — the training data re-splits
    # deterministically over the survivors (data/splitter is a pure
    # function of paths x n_workers) and the job continues instead of
    # failing.  Also unlocks the explicit resize (grow/shrink) op.
    elastic: bool = False
    # fleet mesh layout (shifu.tpu.mesh-shape, e.g. "data:2,model:2"):
    # each rank is one single-device process laid out row-major on this
    # mesh.  The register/promotion replies hand every rank its mesh
    # coordinate, and resize validates the new fleet size against the
    # model axis (a reshape the model axis cannot hold refuses cleanly
    # instead of letting workers crash in parse_mesh_shape).  "" = no
    # declared mesh (workers lay out their local devices themselves).
    mesh_spec: str = ""


class Coordinator:
    """Thread-safe job state machine + TCP server."""

    def __init__(self, spec: JobSpec):
        self.spec = spec
        # the job correlation id workers learn at registration; direct
        # API users who never set spec.job_id still get a unique one
        self.job_id = spec.job_id or uuid.uuid4().hex[:8]
        self.state = JobState.REGISTERING
        self.workers: dict[str, WorkerRecord] = {}
        self._by_index: dict[int, str] = {}
        self._lock = threading.RLock()
        self._start_barrier = threading.Event()
        self._epoch_cond = threading.Condition(self._lock)
        self._last_epoch: dict[int, int] = {}  # worker_index -> max epoch reported
        self._created_at = time.monotonic()
        # SPMD fleet generations: bumped on fleet restart; the submitter
        # watches this to kill + relaunch every worker process
        self._generation = 0
        self._gen_started_at = self._created_at
        self._plan_cond = threading.Condition(self._lock)
        self._plans: dict[int, dict] = {}  # worker_index -> execution plan
        # worker_index -> shard line count; seeded from the spec, updated
        # from workers' sync_plan reports, survives fleet restarts
        self._shard_lines: dict[int, int] = {
            i: int(n)
            for i, n in enumerate(spec.shard_lines or [])
            if n is not None
        }
        self.failure_reason: str | None = None
        # control-plane metrics (obs/registry.py): the coordinator's
        # scrape surface — rendered through the SAME registry/renderer as
        # serve's /metrics (the `metrics` RPC op), so fleet dashboards
        # read one text format everywhere.  Counters pre-registered so
        # the full set exposes from the first scrape.
        self.registry = MetricsRegistry()
        for name in ("registrations_total", "epochs_published_total",
                     "fleet_restarts_total", "health_trips_total",
                     "rollbacks_total", "worker_expiries_total",
                     "worker_failures_total", "op_replays_total",
                     "standby_promotions_total", "resplits_total"):
            self.registry.counter(name)
        self.aggregator = EpochAggregator(
            spec.n_workers, board_path=spec.board_path,
            on_epoch_complete=self._on_epoch_published,
        )
        # fleet early stopping: decided HERE on full-quorum epoch
        # aggregates, delivered via the epoch barrier so every worker
        # stops after the same epoch (see JobSpec.early_stop_*)
        self._early_stopper = None
        self._stop_after_epoch: int | None = None
        self.stop_reason: str | None = None
        # non-SPMD: per-epoch chief stats — the criteria must judge the
        # model that gets EXPORTED, not a fleet mean of independent models
        self._chief_stats: dict[int, EpochStats] = {}
        if spec.early_stop_ks > 0 or spec.early_stop_patience > 0:
            if not spec.sync_epochs:
                # validated, not silently mutated: the builder of the spec
                # owns the invariant (early_stop_spec_kwargs sets it), and
                # a direct API user must opt in knowingly
                raise ValueError(
                    "JobSpec.early_stop_* requires sync_epochs=True: the "
                    "stop decision is delivered through the per-epoch "
                    "barrier so every worker stops after the same epoch"
                )
            from shifu_tensorflow_tpu.train.trainer import EarlyStopper

            self._early_stopper = EarlyStopper(
                target_ks=spec.early_stop_ks,
                patience=spec.early_stop_patience,
            )
        if spec.elastic and not spec.sync_epochs:
            # validated, not silently mutated (the early-stop rule): the
            # shrink/release and re-split directives are delivered ONLY
            # through the per-epoch barrier — without sync_epochs the
            # survivors would keep training their old shards and a
            # released rank would never learn it left the membership
            raise ValueError(
                "JobSpec.elastic requires sync_epochs=True: the elastic "
                "re-split/release directives are delivered through the "
                "per-epoch barrier (elastic_spec_kwargs sets it)"
            )
        self.liveness = LivenessMonitor(
            interval_ms=spec.heartbeat_interval_ms,
            max_missed=spec.max_missed_heartbeats,
            on_expired=self._on_worker_expired,
            on_recovered=self._on_worker_recovered,
        )
        self._failed_restarts = 0
        # restart-budget burn times (monotonic): the budget itself stays
        # lifetime-scoped (parity with the reference's fault envelope),
        # but the metrics op exports the burn inside a sliding window so
        # an operator can see the budget draining BEFORE it exhausts —
        # the PR-5 serve supervisor learned this the hard way (rc 4 was
        # the first visible symptom)
        self._restart_times: list[float] = []
        # ---- elastic fleet (JobSpec.standby_workers / .elastic) ----
        # hot standbys: registered with role=standby, no rank, waiting on
        # the standby_wait long-poll for a promotion
        self.standbys: dict[str, WorkerRecord] = {}
        self._standby_cond = threading.Condition(self._lock)
        #: promotion history (diagnostics + `obs fleet` render): one dict
        #: per promotion with rank, ids, epoch, why, and — once the
        #: standby's wait poll claims it — the takeover latency
        self.promotions: list[dict] = []
        # active membership: the rank indices the fleet currently expects
        # at every barrier/quorum.  Starts as range(n_workers); an
        # elastic shrink (or resize) changes it and re-splits the data
        # over the survivors (split_generation bumps so workers learn
        # their new shard through the epoch barrier)
        self._active_indices: set[int] = set(range(spec.n_workers))
        self._split_generation = 0
        # rank -> shard assignment under the CURRENT split: seeded from
        # the spec, rewritten wholesale by _resplit_over.  register()
        # reads THIS (never spec.shards directly) so a rank grown past
        # the original width — or re-split before its worker registered
        # — is handed the current split's shard, not a stale or
        # out-of-range one
        # (tolerates placeholder shards without .paths — in-memory test
        # fleets construct JobSpec(shards=[None]))
        self._rank_shards: dict[int, tuple[str, ...]] = {
            i: tuple(getattr(s, "paths", None) or ())
            for i, s in enumerate(spec.shards)
        }
        # per-path byte sizes, stat'ed ONCE here (construction runs
        # before the server loop, so no RPC blocks behind it) and fed to
        # every elastic re-split — which runs under self._lock, where a
        # live stat sweep would stall heartbeats (training data is
        # immutable for the life of a job, so the sizes never go stale).
        # Only re-splits consume the sizes, and those are elastic-only:
        # the default path must not re-pay the stat sweep make_job_spec
        # just ran
        self._path_sizes: dict[str, int] = {}
        if spec.elastic:
            from shifu_tensorflow_tpu.data.splitter import _size_safe

            self._path_sizes = {
                p: _size_safe(p)
                for paths in self._rank_shards.values() for p in paths
            }
        # workers released by a resize shrink: they learn it at their
        # next epoch barrier and exit cooperatively.  Membership-derived
        # and NEVER consumed on delivery — a lost reply must redeliver
        # at the released worker's next barrier (the same
        # compare-don't-store discipline the resplit directive follows)
        self._released_ids: set[str] = set()
        # health-rollback state: count, the accumulated LR back-off, the
        # skip directive for the offending batch window, and the last
        # unhealthy report's diagnostics (bundled into failures)
        self._rollbacks = 0
        self._lr_scale = 1.0
        self._skip_directive: dict | None = None
        self._last_unhealthy: dict | None = None
        # non-SPMD hung workers the submitter must SIGKILL (their training
        # thread is wedged; they cannot exit cooperatively)
        self._pending_kills: list[str] = []
        self._server: "_Server | None" = None
        # at-most-once delivery for retried non-idempotent ops: the client
        # stamps register/epoch/complete with a per-LOGICAL-call token; a
        # redelivery (reply lost, transport retried) replays the cached
        # response instead of re-applying — a retried `complete(exit=1)`
        # must not burn two restart-budget units, a retried register must
        # not re-count a worker
        self._op_cache: OrderedDict[str, dict] = OrderedDict()
        self.op_replays = 0
        # bulk scoring plane (score/job.ScoreJob): attached by a score
        # driver, routed by the four score_* / lease_* / shard_commit
        # ops — the lease table lives in the job object, not here, so
        # the coordinator stays a router and the table stays unit-
        # testable without a socket in sight
        self._score_job = None

    def attach_score_job(self, job) -> None:
        """Install the active bulk-score job (score/job.ScoreJob); its
        lease/commit ops dispatch through this coordinator's RPC plane
        and ride the same token replay cache as every other
        non-idempotent op."""
        with self._lock:
            self._score_job = job

    # ---- policy ----
    @property
    def max_restarts(self) -> int:
        return (
            int(self.spec.max_worker_failure_ratio * self.spec.n_workers)
            + self.spec.spare_restarts
        )

    def _fail(self, reason: str) -> None:
        with self._lock:
            # terminal states are sticky: a job that FINISHED during the
            # caller's last poll interval must not be re-marked FAILED (e.g.
            # the submitter's timeout branch racing the chief's completion),
            # and the first failure reason must not be overwritten
            if self.state in (JobState.FINISHED, JobState.FAILED):
                return
            self.state = JobState.FAILED
            self.failure_reason = reason
            log.error("job FAILED: %s", reason)
            self._start_barrier.set()  # release anyone waiting
            self._epoch_cond.notify_all()
            self._plan_cond.notify_all()
        obs_journal.emit("job_failed", plane="coordinator", reason=reason)

    def _on_epoch_published(self, summary) -> None:
        """EpochAggregator quorum hook: the fleet-level epoch record."""
        self.registry.inc("epochs_published_total")
        obs_journal.emit(
            "epoch_summary", plane="coordinator",
            epoch=summary.epoch, n_workers=summary.n_workers,
            mean_train_loss=summary.mean_training_loss,
            mean_valid_loss=summary.mean_valid_loss,
            ks=summary.ks, auc=summary.auc,
            slowest_worker=summary.slowest_worker,
            slowest_time_s=round(summary.slowest_time_s, 4),
        )

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def _expected(self) -> int:
        """Ranks the current membership expects (caller holds the lock).
        Equals spec.n_workers until an elastic shrink/resize."""
        return len(self._active_indices)

    # ---- worker lifecycle (all called under the TCP handlers) ----
    def _mesh_info(self, worker_index: int) -> dict[str, Any] | None:
        """The rank's place on the declared fleet mesh — spec plus this
        rank's row-major coordinate — or None when no mesh is declared
        (or the spec cannot lay over the current fleet size; workers
        then fall back to their local default and the mismatch surfaces
        through their own parse error)."""
        if not self.spec.mesh_spec:
            return None
        n = self._expected()
        try:
            return {
                "spec": self.spec.mesh_spec,
                "shape": parse_mesh_shape(self.spec.mesh_spec, n),
                "coord": mesh_coord(self.spec.mesh_spec, n, worker_index),
            }
        except ValueError:
            return None

    def register(
        self,
        worker_id: str,
        worker_index: int | None = None,
        host: str | None = None,
        jax_port: int | None = None,
        role: str = "worker",
    ) -> dict[str, Any]:
        """``worker_index`` pins the caller to a specific slot (the submitter
        launches worker i with index i, so chief identity is deterministic,
        not registration-order — unlike the reference, where backups/PS
        re-derive indices by string-splitting the final cluster JSON,
        TensorflowTaskExecutor.java:122-148).  Without a pin, the lowest
        free index is assigned first-come."""
        with self._lock:
            if self.state == JobState.FAILED:
                return {"ok": False, "error": self.failure_reason}
            if role == "standby":
                return self._register_standby(worker_id, host)
            rec = self.workers.get(worker_id)
            if rec is None:
                if len(self.workers) >= self._expected():
                    return {"ok": False, "error": "cluster full"}
                if worker_index is None:
                    worker_index = min(
                        i
                        for i in sorted(self._active_indices)
                        if i not in self._by_index
                    )
                elif worker_index not in self._active_indices:
                    return {
                        "ok": False,
                        "error": (
                            f"worker_index {worker_index} not in the active "
                            f"membership {sorted(self._active_indices)}"
                        ),
                    }
                elif worker_index in self._by_index:
                    return {
                        "ok": False,
                        "error": (
                            f"worker_index {worker_index} already taken by "
                            f"{self._by_index[worker_index]!r}"
                        ),
                    }
                # a rank shrunk away and later grown back relaunches
                # under its original id: the stale release directive
                # must not tell the NEW process to exit at its first
                # barrier (the old process learned it and exited; a
                # fresh registration into the active membership is the
                # submitter deliberately refilling the rank)
                self._released_ids.discard(worker_id)
                rec = WorkerRecord(
                    worker_id=worker_id,
                    worker_index=worker_index,
                    shard_paths=self._rank_shards.get(worker_index, ()),
                    registered_at=time.monotonic(),
                )
                self.workers[worker_id] = rec
                self._by_index[rec.worker_index] = worker_id
            else:
                # sticky re-registration after restart: same index + shard
                # (replaces the backup worker inheriting the failed worker's
                # shard, TensorflowSession.java:748-781)
                rec.completed = False
                rec.exit_code = None
            rec.generation = self._generation
            if host is not None:
                rec.host = host
            if jax_port is not None:
                rec.jax_port = int(jax_port)
            self.liveness.register(worker_id)
            if len(self.workers) == self._expected() and all(
                r.generation == self._generation
                for r in self.workers.values()
            ):
                if self.state == JobState.REGISTERING:
                    self.state = JobState.TRAINING
                    log.info("all %d workers registered (generation %d): "
                             "TRAINING", self._expected(),
                             self._generation)
                    self.liveness.start()
                self._start_barrier.set()
            self.registry.inc("registrations_total")
            obs_journal.emit(
                "register", plane="coordinator",
                worker=rec.worker_index, worker_id=worker_id,
                generation=self._generation,
                registered=len(self.workers),
                n_workers=self._expected(),
            )
            return {
                "ok": True,
                "worker_index": rec.worker_index,
                "shard": list(rec.shard_paths),
                "n_workers": self._expected(),
                "total_rows": self.spec.total_rows,
                "epochs": self.spec.epochs,
                "state": self.state.value,
                "sync_epochs": self.spec.sync_epochs,
                "spmd": self.spec.spmd,
                "generation": self._generation,
                "job": self.job_id,
                "shard_lines": self._shard_lines.get(rec.worker_index),
                # declared fleet mesh + this rank's coordinate on it (a
                # promoted/relaunched rank shards the same table rows
                # its predecessor held)
                "mesh": self._mesh_info(rec.worker_index),
                # rollback directive: relaunched workers train at the
                # backed-off LR and skip the batch window that tripped
                # the guard.  SPMD: the FLEET directive (identical for
                # every worker — one model must stay in lockstep);
                # non-SPMD: this worker's own rollback state, so a
                # healthy worker relaunched after an unrelated crash
                # never inherits another worker's back-off
                "health": (
                    {
                        "lr_scale": self._lr_scale,
                        "skip": self._skip_directive,
                        "rollbacks": self._rollbacks,
                    }
                    if self.spec.spmd
                    else {
                        "lr_scale": rec.lr_scale,
                        "skip": rec.skip_directive,
                        "rollbacks": self._rollbacks,
                    }
                ),
            }

    # ---- elastic fleet: standby pool + promotion + membership ----
    def _register_standby(self, worker_id: str,
                          host: str | None) -> dict[str, Any]:
        """Admit (or sticky-refresh) a hot standby.  Caller holds the
        lock.  Standbys hold no rank and never gate the start barrier —
        they heartbeat, pre-build their model, and long-poll
        ``standby_wait`` until a rank failure promotes one of them."""
        rec = self.standbys.get(worker_id)
        promoted = self.workers.get(worker_id)
        if promoted is not None:
            # a promoted standby re-registering (e.g. after an SPMD
            # generation bump) is a WORKER now — route it sticky
            return {"ok": False, "error": (
                f"{worker_id!r} was promoted to rank "
                f"{promoted.worker_index}; re-register as a worker")}
        if rec is None:
            rec = WorkerRecord(
                worker_id=worker_id, worker_index=-1, role="standby",
                registered_at=time.monotonic(),
            )
            self.standbys[worker_id] = rec
        if host is not None:
            rec.host = host
        rec.generation = self._generation
        self.liveness.register(worker_id)
        self.registry.inc("registrations_total")
        obs_journal.emit(
            "standby_register", plane="coordinator", worker_id=worker_id,
            standbys=len(self.standbys), generation=self._generation,
        )
        return {
            "ok": True,
            "role": "standby",
            "worker_index": -1,
            "state": self.state.value,
            "spmd": self.spec.spmd,
            "generation": self._generation,
            "job": self.job_id,
            "epochs": self.spec.epochs,
        }

    def standby_wait(self, worker_id: str,
                     timeout_s: float = 10.0) -> dict[str, Any]:
        """Standby long-poll: block until this standby is promoted into a
        rank, the job reaches a terminal state, or ``timeout_s`` passes
        (the standby then re-polls — each poll doubles as liveness
        evidence beside its heartbeat thread).  The promotion reply is a
        superset of the worker register reply, so the caller can enter
        the normal training path with it."""
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        self.liveness.beat(worker_id)
        with self._standby_cond:
            while True:
                if self.state in (JobState.FINISHED, JobState.FAILED):
                    return {"ok": False, "abort": True,
                            "state": self.state.value,
                            "error": self.failure_reason}
                rec = self.workers.get(worker_id)
                if rec is not None and rec.worker_index >= 0:
                    # promoted: stamp the takeover claim (latency from
                    # the promote decision to this poll returning)
                    for p in reversed(self.promotions):
                        if (p["standby_id"] == worker_id
                                and p.get("claim_latency_s") is None):
                            p["claim_latency_s"] = round(
                                time.monotonic() - p["_promoted_mono"], 4)
                            obs_journal.emit(
                                "standby_claim", plane="coordinator",
                                worker=rec.worker_index,
                                worker_id=worker_id,
                                latency_s=p["claim_latency_s"],
                            )
                            break
                    reply = {
                        "ok": True,
                        "promoted": True,
                        "worker_index": rec.worker_index,
                        "shard": list(rec.shard_paths),
                        "n_workers": self._expected(),
                        "total_rows": self.spec.total_rows,
                        "epochs": self.spec.epochs,
                        "state": self.state.value,
                        "sync_epochs": self.spec.sync_epochs,
                        "spmd": self.spec.spmd,
                        "generation": self._generation,
                        "job": self.job_id,
                        "shard_lines": self._shard_lines.get(
                            rec.worker_index),
                        # the promoted rank inherits the dead rank's mesh
                        # coordinate with its index — its table shard is
                        # the dead rank's table shard
                        "mesh": self._mesh_info(rec.worker_index),
                        "health": {
                            "lr_scale": (self._lr_scale if self.spec.spmd
                                         else rec.lr_scale),
                            "skip": (self._skip_directive if self.spec.spmd
                                     else rec.skip_directive),
                            "rollbacks": self._rollbacks,
                        },
                    }
                    return reply
                if worker_id not in self.standbys and rec is None:
                    return {"ok": False,
                            "error": f"unknown standby {worker_id}"}
                if time.monotonic() >= deadline:
                    return {"ok": True, "promoted": False,
                            "state": self.state.value}
                self._standby_cond.wait(timeout=0.2)

    def _eligible_standbys(self) -> list[WorkerRecord]:
        """Standbys eligible for promotion, freshest heartbeat first.
        Caller holds the lock.  A standby currently EXPIRED by the
        liveness monitor is skipped even if it later flaps back — a
        promotion must land on a rank that is provably alive right now,
        not one the monitor has written off."""
        expired = self.liveness.expired()
        ages = self.liveness.ages()
        out = [s for s in self.standbys.values()
               if s.worker_id not in expired]
        out.sort(key=lambda s: ages.get(s.worker_id, float("inf")))
        return out

    def _promote_standby(self, rec: WorkerRecord, why: str) -> bool:
        """Promote the freshest-heartbeat live standby into ``rec``'s
        rank.  Caller holds the lock.  Returns False when no eligible
        standby exists (caller falls back to the restart/relaunch
        policy).  Promotion is FREE — it consumes a standby, not restart
        budget — and non-SPMD survivors never see it: their barriers
        simply hold until the promoted rank catches up."""
        eligible = self._eligible_standbys()
        if not eligible:
            return False
        standby = eligible[0]
        skipped = [s.worker_id for s in self.standbys.values()
                   if s.worker_id in self.liveness.expired()]
        del self.standbys[standby.worker_id]
        idx = rec.worker_index
        # the standby inherits the dead rank's identity wholesale: index,
        # shard, rollback state (non-SPMD scoping), restart accounting
        standby.role = "worker"
        standby.worker_index = idx
        standby.shard_paths = rec.shard_paths
        standby.generation = self._generation
        standby.lr_scale = rec.lr_scale
        standby.skip_directive = rec.skip_directive
        standby.restarts = rec.restarts
        standby.completed = False
        standby.exit_code = None
        self.workers.pop(rec.worker_id, None)
        self.liveness.unregister(rec.worker_id)
        # the "dead" process may only be FLAPPED (GC pause, partition):
        # if it wakes after the takeover, its next epoch barrier must
        # hand it the cooperative-exit directive the resize shrink uses
        # — otherwise two live processes train rank ``idx``'s shard.
        # Never discarded for this id: the submitter relaunches by
        # active_worker_ids(), which maps the rank to the standby.
        self._released_ids.add(rec.worker_id)
        self.workers[standby.worker_id] = standby
        self._by_index[idx] = standby.worker_id
        ages = self.liveness.ages()
        promo = {
            "worker_index": idx,
            "old_id": rec.worker_id,
            "standby_id": standby.worker_id,
            "why": why,
            "epoch": self._last_epoch.get(idx, -1),
            "hb_age_s": round(ages.get(standby.worker_id, 0.0), 3),
            "ts": time.time(),
            "_promoted_mono": time.monotonic(),
            "claim_latency_s": None,
        }
        self.promotions.append(promo)
        self.registry.inc("standby_promotions_total")
        log.warning(
            "promoting standby %s into rank %d (%s); heartbeat age "
            "%.3fs, %d standby(s) left",
            standby.worker_id, idx, why, promo["hb_age_s"],
            len(self.standbys),
        )
        obs_journal.emit(
            "standby_promote", plane="coordinator",
            worker=idx, worker_id=standby.worker_id,
            old_worker_id=rec.worker_id, why=why,
            epoch=promo["epoch"], hb_age_s=promo["hb_age_s"],
            standbys_left=len(self.standbys),
            skipped_expired=skipped,
            generation=self._generation,
        )
        self._standby_cond.notify_all()
        return True

    def _all_data_paths(self) -> list[str]:
        """Union of every active rank's shard paths (deterministic
        order) — the re-split input.  Caller holds the lock."""
        paths: set[str] = set()
        for rec in self.workers.values():
            paths.update(rec.shard_paths)
        for shard_paths in self._rank_shards.values():
            paths.update(shard_paths)
        for shard in self.spec.shards:
            paths.update(getattr(shard, "paths", None) or ())
        return sorted(paths)

    def _resplit_over(self, indices: list[int], why: str) -> None:
        """Deterministically re-split the training data over ``indices``
        and update membership.  Caller holds the lock.  Workers learn
        their new shard through the epoch barrier (``resplit`` directive,
        keyed by split_generation) — the streaming paths apply it at
        their next epoch boundary; in-memory workers pick it up on
        relaunch (their coordinator record already carries it)."""
        from shifu_tensorflow_tpu.data.splitter import split_size_aware

        indices = sorted(indices)
        paths = self._all_data_paths()
        # sizes were stat'ed once at construction (outside the serving
        # lock): re-splitting holds self._lock, and a live stat sweep
        # over a slow filesystem here would stall heartbeats long
        # enough to expire healthy workers mid-recovery
        shards = split_size_aware(paths, len(indices),
                                  sizes=self._path_sizes)
        # the rank->shard map is rewritten WHOLESALE: ranks whose worker
        # has not registered yet (a grown rank) get their shard from
        # here at registration time
        self._rank_shards = {
            idx: tuple(shard.paths)
            for shard, idx in zip(shards, indices)
        }
        for idx in indices:
            wid = self._by_index.get(idx)
            rec = self.workers.get(wid) if wid else None
            if rec is not None:
                rec.shard_paths = self._rank_shards[idx]
        self._active_indices = set(indices)
        self._split_generation += 1
        # cached per-rank line counts describe the OLD split; workers
        # recount their new shard once and re-report through sync_plan
        self._shard_lines.clear()
        self.registry.inc("resplits_total")
        log.warning("re-split %d data file(s) over ranks %s "
                    "(split generation %d): %s", len(paths), indices,
                    self._split_generation, why)
        obs_journal.emit(
            "resplit", plane="coordinator",
            split_generation=self._split_generation,
            ranks=indices, n_files=len(paths), why=why,
        )
        # barriers re-evaluate against the new membership: a quorum the
        # dead rank was holding open may be complete now
        self._epoch_cond.notify_all()
        self._plan_cond.notify_all()
        self._standby_cond.notify_all()

    def _shrink_membership(self, rec: WorkerRecord, why: str) -> bool:
        """Elastic fallback: drop ``rec``'s rank from the membership and
        re-split its data over the survivors instead of failing the job.
        Caller holds the lock.  Refused (False) for the chief (rank 0
        owns the exported model — nothing to shrink onto) and when no
        survivor would remain."""
        survivors = sorted(self._active_indices - {rec.worker_index})
        if not self.spec.elastic or rec.worker_index == 0 or not survivors:
            return False
        if len(self._all_data_paths()) < len(survivors):
            # placeholder/in-memory shards (no data paths) or fewer
            # files than survivors: split_size_aware would raise AFTER
            # the membership mutation below, wedging the job half-shrunk
            # inside the liveness callback — refuse up front and let the
            # caller's restart/failure policy decide instead
            return False
        self.workers.pop(rec.worker_id, None)
        self._by_index.pop(rec.worker_index, None)
        self.liveness.unregister(rec.worker_id)
        # same flap hazard as promotion: a shrunk-away process that
        # wakes up must learn at its next barrier that the re-split
        # handed its rows to the survivors, and exit instead of
        # training them in duplicate
        self._released_ids.add(rec.worker_id)
        self._last_epoch.pop(rec.worker_index, None)
        self._plans.pop(rec.worker_index, None)
        self._resplit_over(survivors, f"shrink after {why}")
        self.aggregator.set_expected(len(survivors))
        return True

    def resize(self, n_workers: int) -> dict[str, Any]:
        """Explicit elastic grow/shrink to ``n_workers`` ranks (admin op;
        non-SPMD, requires JobSpec.elastic).  Grow adds vacant ranks
        (the submitter launches workers for them — poll
        ``pending_indices``); shrink releases the highest ranks at their
        next epoch barrier.  Either way the data re-splits
        deterministically over the new membership."""
        with self._lock:
            if not self.spec.elastic:
                return {"ok": False,
                        "error": "resize needs JobSpec.elastic=True "
                                 f"({K.ELASTIC})"}
            if self.spec.spmd:
                return {"ok": False, "error": (
                    "resize is non-SPMD only: SPMD membership is pinned "
                    "by the jax.distributed process count for the job's "
                    "lifetime")}
            n = int(n_workers)
            if n < 1:
                return {"ok": False, "error": "n_workers must be >= 1"}
            if self.spec.mesh_spec:
                # a resize IS a mesh reshape: the new fleet must still
                # hold the declared model axis (table shards cannot be
                # rebalanced onto a rank count the axis does not divide)
                # — refuse cleanly instead of letting every relaunched
                # worker crash in parse_mesh_shape
                try:
                    parse_mesh_shape(self.spec.mesh_spec, n)
                except ValueError as e:
                    return {"ok": False, "error": (
                        f"resize to {n} rank(s) is an invalid mesh "
                        f"reshape: {e}")}
            current = sorted(self._active_indices)
            if n == len(current):
                return {"ok": True, "ranks": current, "changed": False}
            if n < len(current):
                keep, drop = current[:n], current[n:]
                if 0 in drop:
                    return {"ok": False,
                            "error": "cannot shrink away the chief"}
                if len(self._all_data_paths()) < n:
                    # validate BEFORE the drop loop mutates membership:
                    # split_size_aware raising mid-mutation would leave
                    # released workers still in the barrier quorum
                    return {"ok": False, "error": (
                        f"cannot shrink to {n} ranks: only "
                        f"{len(self._all_data_paths())} data file(s) to "
                        "re-split (need at least one per rank)")}
                for idx in drop:
                    wid = self._by_index.pop(idx, None)
                    rec = self.workers.pop(wid, None) if wid else None
                    if rec is not None:
                        self.liveness.unregister(rec.worker_id)
                        self._released_ids.add(rec.worker_id)
                    self._last_epoch.pop(idx, None)
                    self._plans.pop(idx, None)
                self._resplit_over(keep, f"resize to {n}")
            else:
                if len(self._all_data_paths()) < n:
                    return {"ok": False, "error": (
                        f"cannot grow to {n} ranks: only "
                        f"{len(self._all_data_paths())} data file(s) to "
                        "split (need at least one per rank)")}
                grown = current + [i for i in range(
                    max(current) + 1 + n - len(current))
                    if i not in current][:n - len(current)]
                self._resplit_over(grown, f"resize to {n}")
            self.aggregator.set_expected(n)
            if self.spec.mesh_spec:
                obs_journal.emit(
                    "mesh_reshape", plane="coordinator",
                    spec=self.spec.mesh_spec, n_workers=n,
                    shape=parse_mesh_shape(self.spec.mesh_spec, n),
                )
            return {"ok": True, "ranks": sorted(self._active_indices),
                    "changed": True,
                    "split_generation": self._split_generation}

    def pending_indices(self) -> list[int]:
        """Active ranks with no registered worker — after a grow, the
        submitter launches one worker per pending index."""
        with self._lock:
            return sorted(i for i in self._active_indices
                          if i not in self._by_index)

    def active_worker_ids(self) -> dict[int, str]:
        """index -> worker_id for the CURRENT membership (a promoted
        standby occupies its rank under its own id) — the submitter's
        relaunch identity map; relaunching by the original launch names
        would collide with promoted standbys."""
        with self._lock:
            return {i: wid for i, wid in sorted(self._by_index.items())
                    if i in self._active_indices}

    def standby_ids(self) -> list[str]:
        """Unpromoted standbys (submitter: skip these on fleet-restart
        kills — they hold no collective state and stay warm)."""
        with self._lock:
            return sorted(self.standbys)

    _LOOPBACK = LOOPBACK_HOSTS

    def _cluster_info(self) -> dict[str, Any]:
        """SPMD bring-up info: where the chief's jax coordination service
        lives.  Meaningful only once every worker of the current generation
        has registered (the await_start barrier guarantees that).

        Raises when the chief registered a loopback address but peers
        registered routable ones: those peers would try to reach the jax
        coordination service at THEIR OWN 127.0.0.1 and hang to a timeout —
        correct on one machine, silently wrong on two (round-2 Weak #6).
        """
        chief_id = self._by_index.get(0)
        chief = self.workers.get(chief_id) if chief_id else None
        chief_host = (chief.host if chief else "") or "127.0.0.1"
        # SPMD only: non-SPMD workers never dial chief_host/jax_port, so a
        # mixed loopback/routable topology is fine there
        if (self.spec.spmd and self.spec.n_workers > 1
                and chief_host in self._LOOPBACK):
            remote = sorted(
                {
                    r.host
                    for r in self.workers.values()
                    if r.host and r.host not in self._LOOPBACK
                }
            )
            if remote:
                raise ValueError(
                    f"chief registered loopback host {chief_host!r} but "
                    f"peers registered {remote}; SPMD peers cannot reach "
                    f"the jax coordination service there — set "
                    f"WorkerConfig.host to a routable address on every "
                    f"worker (the ssh launcher does this from its hosts "
                    f"list)"
                )
        return {
            "chief_host": chief_host,
            "jax_port": chief.jax_port if chief else 0,
            "n_workers": self._expected(),
            "generation": self._generation,
        }

    def await_start(self, timeout_s: float | None = None) -> dict[str, Any]:
        # registration deadline is absolute (measured from job creation, or
        # from the current fleet generation's start), not per-call — late
        # callers can't extend the window, and a short-timeout status probe
        # can't kill the job
        with self._lock:
            barrier = self._start_barrier  # this generation's barrier
            gen_start = self._gen_started_at
        remaining = self.spec.registration_timeout_s - (
            time.monotonic() - gen_start
        )
        wait = max(0.0, remaining)
        if timeout_s is not None:
            wait = min(wait, timeout_s)
        ok = barrier.wait(timeout=wait)
        with self._lock:
            if self.state == JobState.FAILED:
                return {"ok": False, "error": self.failure_reason}
            if ok:
                try:
                    cluster = self._cluster_info()
                except ValueError as e:
                    # misconfigured topology: fail the job with the clear
                    # message instead of letting peers hang on a connect
                    self._fail(str(e))
                    return {"ok": False, "error": self.failure_reason}
                return {
                    "ok": True,
                    "state": self.state.value,
                    "cluster": cluster,
                }
            if time.monotonic() - gen_start >= self.spec.registration_timeout_s:
                self._fail(
                    f"registration timeout: {len(self.workers)}/"
                    f"{self.spec.n_workers} workers after "
                    f"{self.spec.registration_timeout_s:.0f}s"
                )
                return {"ok": False, "error": self.failure_reason}
            # caller's own (shorter) timeout expired; job still registering
            return {"ok": False, "error": "await timeout", "retryable": True}

    def check_registration_deadline(self) -> None:
        """Enforce the registration deadline from the CONTROL side: the
        deadline used to live only inside await_start(), i.e. it was
        policed by the very workers whose absence it guards against — a
        fleet that never launches (bad image, dead hosts) left the job
        REGISTERING until the job timeout.  The submitter polls this."""
        with self._lock:
            if self.state != JobState.REGISTERING:
                return
            elapsed = time.monotonic() - self._gen_started_at
            if elapsed >= self.spec.registration_timeout_s:
                self._fail(
                    f"registration timeout: {len(self.workers)}/"
                    f"{self.spec.n_workers} workers after "
                    f"{self.spec.registration_timeout_s:.0f}s"
                )

    def sync_plan(
        self, worker_id: str, plan: dict, timeout_s: float | None = None
    ) -> dict[str, Any]:
        """Barrier agreeing the per-epoch execution plan across the fleet.

        Each SPMD worker reports its local view — per-epoch step counts
        derived from its shard size, and the latest checkpoint epoch visible
        on its filesystem — and receives the fleet agreement: the MAX step
        counts (short shards pad with zero-weight batches; every process
        must run identical step sequences or the all-reduce deadlocks) and
        the MIN visible checkpoint (guards the race where the chief saved a
        new checkpoint between two workers' directory listings).
        """
        deadline = time.monotonic() + (
            timeout_s
            if timeout_s is not None
            else self.spec.epoch_barrier_timeout_s
        )
        with self._plan_cond:
            rec = self.workers.get(worker_id)
            if rec is None:
                return {"ok": False, "error": f"unknown worker {worker_id}"}
            gen = self._generation
            self._plans[rec.worker_index] = dict(plan)
            if plan.get("shard_lines") is not None:
                # cache the worker's one-time shard count for its relaunches
                self._shard_lines[rec.worker_index] = int(plan["shard_lines"])
            self._plan_cond.notify_all()
            while True:
                if self.state == JobState.FAILED:
                    return {
                        "ok": False,
                        "abort": True,
                        "error": self.failure_reason,
                    }
                if self._generation != gen:
                    return {"ok": False, "restart": True}
                if len(self._plans) >= self._expected() and all(
                        i in self._plans for i in self._active_indices):
                    plans = [self._plans[i]
                             for i in sorted(self._active_indices)]
                    return {
                        "ok": True,
                        "train_steps": max(
                            int(p.get("train_steps", 0)) for p in plans
                        ),
                        "valid_steps": max(
                            int(p.get("valid_steps", 0)) for p in plans
                        ),
                        "ckpt_epoch": min(
                            int(p.get("ckpt_epoch", -1)) for p in plans
                        ),
                    }
                if time.monotonic() >= deadline:
                    missing = [
                        i
                        for i in sorted(self._active_indices)
                        if i not in self._plans
                    ]
                    return {
                        "ok": False,
                        "error": (
                            f"sync_plan timeout for {worker_id!r} "
                            f"(workers missing: {missing})"
                        ),
                    }
                self._plan_cond.wait(timeout=0.2)

    def heartbeat(self, worker_id: str) -> dict[str, Any]:
        self.liveness.beat(worker_id)
        with self._lock:
            out = {
                "ok": True,
                "abort": self.state == JobState.FAILED,
                "generation": self._generation,
            }
            if worker_id in self._released_ids:
                # a promoted-over or shrunk-away flapper may never call
                # epoch_barrier (sync_epochs can be off outside the
                # elastic path): the heartbeat is the one channel EVERY
                # worker polls, so the cooperative-exit directive rides
                # it too
                out["released"] = True
            return out

    def report_epoch(self, stats_dict: dict[str, Any]) -> dict[str, Any]:
        stats = EpochStats(**stats_dict)
        # fleet leg (obs/fleet.py): per-rank skew digests fed from the
        # phase summary the worker attached (EpochStats.phases, the same
        # budget_fields drain its own journal got) — straggler
        # detect/clear, the fleet_skew record on epoch quorum, and the
        # slo-straggler-skew watchdog signal all run in the reporter's
        # request.  One is-None check when obs is off.
        from shifu_tensorflow_tpu.obs import fleet as obs_fleet

        mon = obs_fleet.active()
        if mon is not None:
            mon.observe_epoch(
                stats.worker_index, stats.current_epoch,
                stats.training_time_s, phases=stats.phases,
                n_workers=self.spec.n_workers,
            )
        if (
            self._early_stopper is not None
            and not self.spec.spmd
            and stats.worker_index == 0
        ):
            with self._lock:
                # nothing left to decide once the stop is set — storing
                # further epochs would only leak
                if self._stop_after_epoch is None:
                    self._chief_stats[stats.current_epoch] = stats
        summary = self.aggregator.report(stats)
        if summary is not None and self._early_stopper is not None:
            # full quorum for this epoch: evaluate the FLEET criteria.
            # Runs in the LAST reporter's request, before the barrier
            # notify below — so by the time the barrier releases, the
            # decision is already visible to every waiter.
            #
            # SPMD (one shared model): the quorum MEAN of shard-local
            # KS/valid-loss is a fair estimate of the one model.
            # Non-SPMD (independent models): judge the CHIEF's own stats —
            # only the chief's model is exported, and a fleet mean could
            # clear the target while the exported model is below it.
            with self._lock:
                if self._stop_after_epoch is None:
                    if self.spec.spmd:
                        eval_stats = EpochStats(
                            worker_index=-1,
                            current_epoch=summary.epoch,
                            training_loss=summary.mean_training_loss,
                            valid_loss=summary.mean_valid_loss,
                            training_time_s=summary.mean_training_time_s,
                            valid_time_s=summary.mean_valid_time_s,
                            global_step=0,
                            ks=summary.ks,
                            auc=summary.auc,
                        )
                    else:
                        # partial-quorum flushes without the chief skip
                        # evaluation (nothing exported to judge)
                        eval_stats = self._chief_stats.pop(
                            summary.epoch, None
                        )
                        # prune entries this epoch leapfrogged (epochs
                        # flushed at partial quorum are never evaluated;
                        # without pruning, restart-heavy jobs leak them)
                        for e in [k for k in self._chief_stats
                                  if k <= summary.epoch]:
                            del self._chief_stats[e]
                    reason = (
                        self._early_stopper.should_stop(eval_stats)
                        if eval_stats is not None
                        else None
                    )
                    if reason:
                        self._stop_after_epoch = summary.epoch
                        self.stop_reason = reason
                        self._chief_stats.clear()  # decided: free the rest
                        log.info("fleet early stop after epoch %d: %s",
                                 summary.epoch, reason)
        with self._epoch_cond:
            prev = self._last_epoch.get(stats.worker_index, -1)
            self._last_epoch[stats.worker_index] = max(prev, stats.current_epoch)
            self._epoch_cond.notify_all()
        return {"ok": True, "abort": self.state == JobState.FAILED}

    def epoch_barrier(
        self, worker_id: str, epoch: int, timeout_s: float | None = None,
        split_generation: int | None = None,
    ) -> dict[str, Any]:
        """Block until every ACTIVE worker index has reported ``epoch``
        (or the job reaches a terminal state).  A failed worker holds the
        barrier; its relaunch — or its promoted standby — re-reports the
        epoch and releases everyone; an elastic shrink removes it from
        the quorum instead.  Sync-SGD semantics at epoch granularity.

        ``split_generation`` is the caller's last-applied re-split: when
        it trails the coordinator's, the success reply carries the
        worker's NEW shard (``resplit`` directive) — echoed per request
        so a lost reply just means redelivery at the next barrier."""
        deadline = time.monotonic() + (
            timeout_s
            if timeout_s is not None
            else self.spec.epoch_barrier_timeout_s
        )
        def _ok() -> dict[str, Any]:
            out = {"ok": True, "state": self.state.value}
            if (split_generation is not None
                    and split_generation < self._split_generation):
                rec = self.workers.get(worker_id)
                if rec is not None:
                    out["resplit"] = {
                        "shard": list(rec.shard_paths),
                        "split_generation": self._split_generation,
                        "n_workers": self._expected(),
                    }
            if self._stop_after_epoch is not None:
                # same value for every worker — the whole fleet stops
                # after the same epoch.  Attached to EVERY success
                # return, including the FINISHED fast path: the chief
                # stopping early flips the job FINISHED, and a peer
                # whose barrier lands after that must still see the
                # stop instead of training its remaining budget
                out["stop_after_epoch"] = self._stop_after_epoch
                out["stop_reason"] = self.stop_reason
            return out

        with self._epoch_cond:
            while True:
                if self.state == JobState.FAILED:
                    return {"ok": False, "abort": True, "error": self.failure_reason}
                if worker_id in self._released_ids:
                    # resize shrink released this rank: the worker exits
                    # cooperatively instead of training a shard the
                    # re-split just handed to the survivors.  NOT
                    # consumed on delivery — a lost reply (the exact
                    # fault the rpc.recv seam models, and this op
                    # carries no dedup token) must redeliver at the
                    # retry, or the released worker trains duplicated
                    # rows for the rest of the job
                    return {"ok": True, "released": True,
                            "state": self.state.value}
                if self.state == JobState.FINISHED:
                    return _ok()
                if all(
                    self._last_epoch.get(i, -1) >= epoch
                    for i in self._active_indices
                ):
                    return _ok()
                if time.monotonic() >= deadline:
                    missing = [
                        i
                        for i in sorted(self._active_indices)
                        if self._last_epoch.get(i, -1) < epoch
                    ]
                    return {
                        "ok": False,
                        "error": (
                            f"epoch barrier timeout for {worker_id!r} "
                            f"(epoch {epoch}; workers missing: {missing})"
                        ),
                    }
                self._epoch_cond.wait(timeout=0.2)

    def complete(self, worker_id: str, exit_code: int) -> dict[str, Any]:
        with self._lock:
            standby = self.standbys.pop(worker_id, None)
            if standby is not None:
                # a standby leaving (job over, or its own crash) just
                # shrinks the pool — no rank failed, no budget charged
                self.liveness.unregister(worker_id)
                obs_journal.emit(
                    "standby_exit", plane="coordinator",
                    worker_id=worker_id, exit_code=exit_code,
                    standbys=len(self.standbys),
                )
                return {"ok": True, "state": self.state.value}
            rec = self.workers.get(worker_id)
            if rec is None:
                return {"ok": False, "error": f"unknown worker {worker_id}"}
            rec.completed = True
            rec.exit_code = exit_code
            self.liveness.unregister(worker_id)
            if exit_code == RESTART_EXIT_CODE:
                # cooperative exit because the fleet is restarting — not a
                # failure; the submitter relaunches this worker into the
                # new generation
                return {"ok": True, "state": self.state.value}
            if exit_code == UNHEALTHY_EXIT_CODE:
                # health-rollback exit: report_unhealthy already charged
                # the budget; the record stays completed-with-nonzero so
                # restartable_workers() offers it for relaunch (non-SPMD)
                return {"ok": True, "state": self.state.value}
            if exit_code != 0:
                # only a failure during an active job consumes budget: after
                # FINISHED the model is already exported, and after FAILED
                # workers exit cooperatively (code 42) — counting those (or
                # letting a chief abort overwrite failure_reason) would mask
                # the root cause
                if self.state in (JobState.REGISTERING, JobState.TRAINING):
                    self._on_worker_failed(rec, f"exit code {exit_code}")
            else:
                # success when the chief completes cleanly (parity:
                # TensorflowApplicationMaster.java:373-376)
                if rec.worker_index == 0 and self.state == JobState.TRAINING:
                    self.state = JobState.FINISHED
                    log.info("chief completed cleanly: FINISHED")
                    self._epoch_cond.notify_all()
                    obs_journal.emit("job_finished", plane="coordinator",
                                     epochs_published=len(
                                         self.aggregator.summaries))
            return {"ok": True, "state": self.state.value}

    # ---- training-health rollback ----
    def report_unhealthy(
        self,
        worker_id: str,
        epoch: int,
        reason: str,
        bad_steps: list | None = None,
        diag: dict | None = None,
        hung: bool = False,
    ) -> dict[str, Any]:
        """A worker's health guard tripped (divergence or hung step).
        Arbitrate ONE fleet-wide rollback: charge the shared restart
        budget AND the health_max_rollbacks cap, accumulate the LR
        back-off, record the skip window for the offending steps, and —
        SPMD — bump the fleet generation so everyone restores the last
        verified checkpoint together.  Budget exhausted → fail fast with
        the diagnostic bundle (last losses/grad norms, per-worker
        heartbeat ages), never hang."""
        with self._lock:
            rec = self.workers.get(worker_id)
            if rec is None:
                return {"ok": False, "error": f"unknown worker {worker_id}"}
            if self.state in (JobState.FINISHED, JobState.FAILED):
                return {"ok": False, "abort": True,
                        "error": self.failure_reason}
            if self.spec.spmd and rec.generation < self._generation:
                # a rollback for this root cause is already underway —
                # peers of the tripping worker report the same NaN (the
                # all-reduce propagated it); only the first consumes budget
                return {"ok": True, "fleet": True, "deduped": True}
            self._rollbacks += 1
            self._last_unhealthy = {
                "worker_id": worker_id,
                "worker_index": rec.worker_index,
                "epoch": int(epoch),
                "reason": reason,
                "bad_steps": list(bad_steps or []),
                "diag": dict(diag or {}),
            }
            # skip window: every reported bad step plus health_skip_window
            # - 1 steps before it (the guard reports the FIRST bad step
            # and its non-finite successors, so the trailing side is
            # already covered by the report itself)
            skip = None
            if bad_steps:
                w = max(0, int(self.spec.health_skip_window) - 1)
                steps = sorted({
                    s
                    for b in bad_steps
                    for s in range(max(0, int(b) - w), int(b) + 1)
                })
                skip = {"epoch": int(epoch), "steps": steps}
            if self.spec.spmd:
                # fleet-wide: one model, one directive
                self._lr_scale *= self.spec.health_lr_backoff
                if skip is not None:
                    self._skip_directive = skip
                applied_scale = self._lr_scale
            else:
                # per-worker: independent models roll back independently
                rec.lr_scale *= self.spec.health_lr_backoff
                if skip is not None:
                    rec.skip_directive = skip
                applied_scale = rec.lr_scale
            log.warning(
                "worker %d unhealthy at epoch %d (%s): rollback %d/%d, "
                "lr_scale -> %g, skip %s",
                rec.worker_index, epoch, reason, self._rollbacks,
                self.spec.health_max_rollbacks, applied_scale, skip,
            )
            self.registry.inc("health_trips_total")
            self.registry.inc("rollbacks_total")
            obs_journal.emit(
                "health_trip", plane="coordinator",
                worker=rec.worker_index, epoch=int(epoch), reason=reason,
                hung=hung, bad_steps=list(bad_steps or [])[:8],
            )
            obs_journal.emit(
                "rollback", plane="coordinator",
                worker=rec.worker_index, epoch=int(epoch),
                rollbacks=self._rollbacks,
                max_rollbacks=self.spec.health_max_rollbacks,
                lr_scale=applied_scale, skip=skip,
                fleet=self.spec.spmd,
            )
            if self._rollbacks > self.spec.health_max_rollbacks:
                self._fail(
                    f"health rollback budget exhausted "
                    f"({self.spec.health_max_rollbacks}) by worker "
                    f"{rec.worker_index} at epoch {epoch}: {reason}; "
                    f"diagnostics: {json.dumps(self.diagnostics())}"
                )
                return {"ok": False, "abort": True,
                        "error": self.failure_reason}
            if self.spec.spmd:
                # shares the crash-restart budget: _fleet_restart charges
                # it and fails the job (with the reason) when exhausted
                self._fleet_restart(
                    f"worker {rec.worker_index} unhealthy at epoch "
                    f"{epoch} ({reason}); rollback {self._rollbacks}/"
                    f"{self.spec.health_max_rollbacks}"
                )
                if self.state == JobState.FAILED:
                    return {"ok": False, "abort": True,
                            "error": self.failure_reason}
                return {"ok": True, "fleet": True}
            # non-SPMD: this worker rolls back alone — charge the shared
            # budget here; the worker exits UNHEALTHY_EXIT_CODE (which
            # complete() treats as already-charged) and is relaunched
            self._failed_restarts += 1
            self._restart_times.append(time.monotonic())
            if self._failed_restarts > self.max_restarts:
                self._fail(
                    f"worker {rec.worker_index} unhealthy at epoch {epoch} "
                    f"({reason}); restart budget {self.max_restarts} "
                    f"exhausted; diagnostics: "
                    f"{json.dumps(self.diagnostics())}"
                )
                return {"ok": False, "abort": True,
                        "error": self.failure_reason}
            rec.restarts += 1
            if hung:
                # the worker's training thread is wedged — it cannot exit
                # on its own; the submitter must SIGKILL it before any
                # relaunch.  Deliberately NOT marked restartable here:
                # restartability waits for mark_worker_killed(), so the
                # relaunch can never race ahead of the kill and become
                # its victim (the submitter's poll loop would otherwise
                # overwrite its process handle and SIGKILL the fresh
                # worker while the zombie lives on).
                self.liveness.unregister(worker_id)
                self._pending_kills.append(worker_id)
            return {"ok": True, "fleet": False}

    def take_pending_kills(self) -> list[str]:
        """Drain the workers the submitter must SIGKILL (hung steps).
        The submitter calls mark_worker_killed() for each once the kill
        has been delivered."""
        with self._lock:
            out, self._pending_kills = self._pending_kills, []
            return out

    def mark_worker_killed(self, worker_id: str) -> None:
        """The submitter delivered the SIGKILL for a hung worker: NOW the
        record becomes restartable (budget was already charged by
        report_unhealthy)."""
        with self._lock:
            rec = self.workers.get(worker_id)
            if rec is not None and not rec.completed:
                rec.completed = True
                rec.exit_code = UNHEALTHY_EXIT_CODE

    # ---- failure handling ----
    def _on_worker_expired(self, worker_id: str) -> None:
        with self._lock:
            # standbys live in their own pool: without this lookup an
            # expired standby never reaches _on_worker_failed's standby
            # branch (no warning, and the pool silently overcounts)
            rec = (self.workers.get(worker_id)
                   or self.standbys.get(worker_id))
            if rec is not None and not rec.completed:
                self._on_worker_failed(rec, "missed heartbeats")

    def _on_worker_recovered(self, worker_id: str) -> None:
        """Liveness flap: a worker written off as expired is beating
        again (long compile / GC pause / healed partition).  If its
        expiry already consumed restart budget or triggered a relaunch,
        that cannot be undone — but the fleet no longer treats the worker
        as permanently gone, and the flap is on the record."""
        with self._lock:
            rec = self.workers.get(worker_id)
            idx = rec.worker_index if rec is not None else -1
        log.warning(
            "worker %d (%s) recovered from liveness expiry (flap #%d)",
            idx, worker_id, self.liveness.flaps,
        )

    def _on_worker_failed(self, rec: WorkerRecord, why: str) -> None:
        if rec.role == "standby" or rec.worker_index < 0:
            # a standby dying never fails a rank: it just leaves the pool
            # (its record stays so a flap can recover it — expiry is
            # already the eligibility gate for promotion)
            log.warning("standby %s failed (%s); %d standby(s) remain "
                        "eligible", rec.worker_id, why,
                        len(self._eligible_standbys()))
            return
        self.registry.inc("worker_failures_total")
        obs_journal.emit("worker_failed", plane="coordinator",
                         worker=rec.worker_index, why=why,
                         generation=rec.generation)
        if self.spec.spmd:
            if rec.generation < self._generation:
                # casualty of a generation that already restarted: one
                # root-cause failure cascades (peers die inside the broken
                # collective, liveness expires the killed process) — only
                # the first event consumes restart budget
                return
            # SPMD: every process participates in every all-reduce, so
            # losing ANY one (chief included — in SPMD the chief holds no
            # state its peers lack; the shared checkpoint has everything)
            # breaks the collective.  Recovery = full fleet restart from the
            # checkpoint.  This consciously widens the reference's
            # chief-short-circuit (TensorflowSession.java:434-452): under
            # SPMD a chief failure is as recoverable as any other.
            #
            # With a live standby the restart is UNCHARGED: the prebuilt
            # standby substitutes into the dead rank (sticky index +
            # shard) and the fleet resumes from the latest VERIFIED epoch
            # (sync_plan agreement) — the standby was the budget.
            if self._promote_standby(rec, why):
                self._fleet_restart(
                    f"worker {rec.worker_index} failed ({why}); standby "
                    f"promoted into rank {rec.worker_index}",
                    charge=False,
                )
                return
            self._fleet_restart(f"worker {rec.worker_index} failed ({why})")
            return
        # non-SPMD: a live standby takes the rank over with ZERO rollback
        # anywhere — survivors' barriers simply hold until the promoted
        # rank restores the latest verified checkpoint and catches up
        if self._promote_standby(rec, why):
            return
        if rec.worker_index == 0:
            # chief short-circuit (TensorflowSession.java:434-452): only
            # a standby promotion (above) can save a dead chief
            self._fail(f"chief worker failed: {why}")
            return
        self._failed_restarts += 1
        self._restart_times.append(time.monotonic())
        if self._failed_restarts > self.max_restarts:
            # elastic fleets SHRINK here instead of failing: drop the
            # rank, re-split its data over the survivors, continue
            if self._shrink_membership(
                    rec, f"worker {rec.worker_index} failed ({why}); "
                         f"restart budget {self.max_restarts} exhausted"):
                return
            self._fail(
                f"worker {rec.worker_index} failed ({why}); restart budget "
                f"{self.max_restarts} exhausted"
            )
        else:
            rec.restarts += 1  # submitter polls status and relaunches

    def request_restart(self, worker_id: str, why: str) -> dict[str, Any]:
        """A worker hit an infrastructure fault it knows a fresh generation
        cures (canonically: the chief's reserved jax port was stolen before
        ``jax.distributed.initialize`` could bind it).  SPMD: bump the
        generation — ONE budgeted restart attributed to the root cause,
        instead of an opaque exit-1 whose cascade the coordinator must
        dedup.  The caller then exits RESTART_EXIT_CODE (not a failure)."""
        with self._lock:  # RLock: held across _fleet_restart so concurrent
            # requesters can't each pass the dedup check and burn N budget
            # units for one root cause
            rec = self.workers.get(worker_id)
            if rec is None:
                return {"ok": False, "error": f"unknown worker {worker_id}"}
            if not self.spec.spmd:
                # non-SPMD workers restart individually: exit nonzero and
                # the submitter relaunches within budget
                return {"ok": True, "fleet": False}
            if rec.generation < self._generation:
                # a restart for this fault is already underway
                return {"ok": True, "fleet": True}
            self._fleet_restart(
                f"worker {rec.worker_index} requested restart ({why})"
            )
            return {"ok": True, "fleet": True}

    def _fleet_restart(self, why: str, charge: bool = True) -> None:
        """Bump the fleet generation: the submitter kills every live worker
        process and relaunches the whole fleet; workers re-register sticky
        (same index, same shard) and resume from the agreed checkpoint.

        ``charge=False`` is the standby-promotion path: the restart
        consumed a prebuilt standby instead of restart budget."""
        with self._lock:
            if self.state in (JobState.FINISHED, JobState.FAILED):
                return
            if charge:
                self._failed_restarts += 1
                self._restart_times.append(time.monotonic())
                if self._failed_restarts > self.max_restarts:
                    self._fail(
                        f"{why}; restart budget {self.max_restarts} "
                        f"exhausted"
                    )
                    return
            self._generation += 1
            log.warning("fleet restart -> generation %d (%s); budget %d/%d "
                        "used", self._generation, why,
                        self._failed_restarts, self.max_restarts)
            self.registry.inc("fleet_restarts_total")
            obs_journal.emit(
                "fleet_restart", plane="coordinator",
                generation=self._generation, why=why,
                restarts_used=self._failed_restarts,
                restart_budget=self.max_restarts,
                charged=charge,
            )
            self._gen_started_at = time.monotonic()
            self._start_barrier = threading.Event()
            self._plans.clear()
            self._last_epoch.clear()
            self.state = JobState.REGISTERING
            for rec in self.workers.values():
                rec.completed = False
                rec.exit_code = None
                rec.restarts += 1
                # stale liveness entries must not double-fire a restart for
                # processes the submitter is about to kill anyway
                self.liveness.unregister(rec.worker_id)
            self._epoch_cond.notify_all()
            self._plan_cond.notify_all()

    def restartable_workers(self) -> list[WorkerRecord]:
        """Workers that failed within budget and await relaunch: both clean
        failures (nonzero exit) and hung workers expired by the liveness
        monitor (which never call complete())."""
        expired = self.liveness.expired()
        with self._lock:
            if self.state == JobState.FAILED:
                return []
            if self.spec.spmd:
                # SPMD recovery is fleet-wide: the submitter watches
                # .generation and relaunches everyone, not individuals
                return []
            return [
                r
                for r in self.workers.values()
                if (r.completed and (r.exit_code or 0) != 0)
                or (not r.completed and r.worker_id in expired)
            ]

    def last_reported_epochs(self) -> dict[str, int]:
        """worker_id -> highest epoch it has reported (locked snapshot);
        the submitter's kill-injection hook keys on this."""
        with self._lock:
            by_index = dict(self._last_epoch)
            return {
                wid: by_index[rec.worker_index]
                for wid, rec in self.workers.items()
                if rec.worker_index in by_index
            }

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "ok": True,
                "state": self.state.value,
                "registered": len(self.workers),
                "n_workers": self.spec.n_workers,
                "failure_reason": self.failure_reason,
                "restarts_used": self._failed_restarts,
                "restart_budget": self.max_restarts,
                "epochs_published": len(self.aggregator.summaries),
                "pending_epochs": self.aggregator.pending_epochs(),
                "spmd": self.spec.spmd,
                "generation": self._generation,
                # rollback visibility: operators (and the drills) can see
                # that a health rollback happened, not just that epochs
                # ran twice
                "rollbacks": self._rollbacks,
                "lr_scale": self._lr_scale,
                # elastic fleet visibility
                "standbys": len(self.standbys),
                "promotions": len(self.promotions),
                "active_workers": sorted(self._active_indices),
                "split_generation": self._split_generation,
            }

    def diagnostics(self) -> dict[str, Any]:
        """The failure-time diagnostic bundle: per-worker last-heartbeat
        ages and liveness state, last reported epochs, restart/rollback
        accounting, and the most recent unhealthy report (last losses,
        grad norms).  Attached to JobResult on every failure and inlined
        into budget-exhaustion failure reasons — a timeout message alone
        tells an operator nothing about WHICH worker went quiet."""
        ages = self.liveness.ages()
        expired = self.liveness.expired()
        with self._lock:
            workers = {}
            for wid, rec in self.workers.items():
                if wid in expired:
                    liveness = "expired"
                elif wid in ages:
                    liveness = "alive"
                else:
                    liveness = "unregistered"
                workers[wid] = {
                    "worker_index": rec.worker_index,
                    "role": rec.role,
                    "liveness": liveness,
                    "last_heartbeat_age_s": (
                        round(ages[wid], 3) if wid in ages else None
                    ),
                    "last_epoch": self._last_epoch.get(
                        rec.worker_index, -1),
                    "restarts": rec.restarts,
                    "completed": rec.completed,
                    "exit_code": rec.exit_code,
                    "lr_scale": rec.lr_scale,
                }
            standbys = {}
            for wid, rec in self.standbys.items():
                standbys[wid] = {
                    "liveness": ("expired" if wid in expired
                                 else "alive" if wid in ages
                                 else "unregistered"),
                    "last_heartbeat_age_s": (
                        round(ages[wid], 3) if wid in ages else None
                    ),
                }
            return {
                "workers": workers,
                "restarts_used": self._failed_restarts,
                "restart_budget": self.max_restarts,
                "rollbacks": self._rollbacks,
                "lr_scale": self._lr_scale,
                "liveness_flaps": self.liveness.flaps,
                "generation": self._generation,
                "last_unhealthy": self._last_unhealthy,
                # elastic fleet: the standby pool and every promotion —
                # rank, ids, epoch, heartbeat age at choice, takeover
                # latency once claimed (internal monotonic stamp elided)
                "standbys": standbys,
                "promotions": [
                    {k: v for k, v in p.items()
                     if not k.startswith("_")}
                    for p in self.promotions
                ],
                "active_workers": sorted(self._active_indices),
                "split_generation": self._split_generation,
            }

    def metrics_text(self) -> str:
        """The control plane's scrape body — same registry types and
        renderer as serve's ``/metrics`` (obs/registry.py), so one
        dashboard stack reads both.  Gauges pulled at render time, the
        same convention ServeMetrics follows."""
        with self._lock:
            self.registry.set_gauge("workers_registered", len(self.workers))
            self.registry.set_gauge("workers_expected", self._expected())
            self.registry.set_gauge("generation", self._generation)
            self.registry.set_gauge("restarts_used", self._failed_restarts)
            self.registry.set_gauge("restart_budget", self.max_restarts)
            # the budget draining must be visible BEFORE it exhausts:
            # remaining headroom plus the burn inside a sliding window
            # (the same 600s window the serve supervisor budgets over) —
            # a burst here is the page; a slow lifetime trickle is not
            self.registry.set_gauge(
                "restart_budget_remaining",
                max(0, self.max_restarts - self._failed_restarts))
            now = time.monotonic()
            self._restart_times = [
                t for t in self._restart_times
                if now - t < RESTART_BURN_WINDOW_S]
            self.registry.set_gauge(
                "restart_budget_burn_window", len(self._restart_times))
            # elastic fleet: pool size, currently-promotable count, and
            # membership width
            self.registry.set_gauge("standby_registered",
                                    len(self.standbys))
            self.registry.set_gauge("standby_available",
                                    len(self._eligible_standbys()))
            self.registry.set_gauge("split_generation",
                                    self._split_generation)
            self.registry.set_gauge("lr_scale", self._lr_scale)
            self.registry.set_gauge(
                "state_info", 1, labels='{state="%s"}' % self.state.value
            )
        text = self.registry.render_prometheus("stpu_coord_")
        # per-worker heartbeat ages: liveness as a SCRAPEABLE series, not
        # just a post-mortem diagnostics bundle — hand-rendered because
        # the per-worker label set shares one metric name, which the
        # one-label-set-per-gauge registry cannot express
        ages = self.liveness.ages()
        if ages:
            with self._lock:
                by_id = {wid: rec.worker_index
                         for wid, rec in self.workers.items()}
            lines = ["# TYPE stpu_coord_heartbeat_age_seconds gauge"]
            for wid in sorted(ages, key=lambda w: by_id.get(w, -1)):
                idx = by_id.get(wid)
                who = wid if idx is None else str(idx)
                lines.append(
                    'stpu_coord_heartbeat_age_seconds{worker="%s"} %.3f'
                    % (who, ages[wid]))
            text += "\n".join(lines) + "\n"
        # fleet leg: per-rank skew/step-time/offset gauges + straggler
        # state + collective byte counters (obs/fleet.py)
        from shifu_tensorflow_tpu.obs import fleet as obs_fleet

        fleet_mon = obs_fleet.active()
        if fleet_mon is not None:
            text += fleet_mon.render_prometheus()
        from shifu_tensorflow_tpu.obs import slo as obs_slo

        watchdog = obs_slo.active()
        if watchdog is not None:
            # the stpu_slo_* gauges append to every scrape surface; on
            # the thread launcher the coordinator shares the process
            # with its workers, so the train watchdog renders here too
            text += watchdog.render_prometheus()
        # device/compiler leg + build identity (same thread-launcher
        # argument: trainers hosted in this process feed exactly this
        # recorder/accountant) — one shared renderer for every scrape
        # surface (obs.device_obs_text)
        from shifu_tensorflow_tpu.obs import device_obs_text

        return text + device_obs_text()

    # ---- TCP plumbing ----
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        """Start the TCP server; returns (host, bound_port)."""
        coord = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                for raw in self.rfile:
                    try:
                        msg = json.loads(raw)
                        resp = coord.dispatch(msg)
                    except Exception as e:  # malformed input must not kill the server
                        resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
                    self.wfile.write((json.dumps(resp) + "\n").encode())
                    self.wfile.flush()

        self._server = _Server((host, port), Handler)
        # 50ms poll: serve_forever's default 0.5s poll makes shutdown()
        # block half a second on average, which dominates short-lived
        # coordinators (one bulk score job runs its own)
        t = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            daemon=True)
        t.start()
        return self._server.server_address[:2]

    _OP_CACHE_MAX = 4096

    def dispatch(self, msg: dict[str, Any]) -> dict[str, Any]:
        """Route one request; replays the cached response for a duplicate
        delivery token (see _op_cache).  The replay window assumes retries
        are SERIAL per logical call — the client only re-sends after its
        previous attempt failed — so two in-flight deliveries of one token
        cannot race the cache.

        Every reply is stamped with the server's receive/send wall times
        (``srv_recv_ts``/``srv_ts``): with the client's own send/receive
        times that is the full NTP four-tuple, from which CoordinatorClient
        estimates its clock offset against the coordinator — no extra
        traffic, and barrier ops that block for minutes server-side cancel
        out of the estimate (obs/fleet.ClockSync).  Stamps are applied
        AFTER the replay cache, per delivery: a replayed response must
        describe THIS exchange's timing, not the original's."""
        t_recv = time.time()
        token = msg.get("token")
        cached = None
        if token is not None:
            with self._lock:
                cached = self._op_cache.get(token)
                if cached is not None:
                    self.op_replays += 1  # under the lock: handler threads
                    self.registry.inc("op_replays_total")
            if cached is not None:
                log.info("replaying cached response for duplicate %s "
                         "delivery (token %s)", msg.get("op"), token)
        if cached is not None:
            resp = cached
        else:
            resp = self._dispatch(msg)
            if token is not None:
                with self._lock:
                    self._op_cache[token] = resp
                    while len(self._op_cache) > self._OP_CACHE_MAX:
                        self._op_cache.popitem(last=False)
        stamped = dict(resp)
        stamped["srv_recv_ts"] = round(t_recv, 6)
        stamped["srv_ts"] = round(time.time(), 6)
        return stamped

    def _dispatch(self, msg: dict[str, Any]) -> dict[str, Any]:
        op = msg.get("op")
        if op == "register":
            return self.register(
                msg["worker_id"],
                msg.get("worker_index"),
                msg.get("host"),
                msg.get("jax_port"),
                msg.get("role") or "worker",
            )
        if op == "standby_wait":
            return self.standby_wait(
                msg["worker_id"], float(msg.get("timeout_s") or 10.0)
            )
        if op == "resize":
            return self.resize(int(msg["n_workers"]))
        if op == "await_start":
            return self.await_start(msg.get("timeout_s"))
        if op == "sync_plan":
            return self.sync_plan(
                msg["worker_id"], msg.get("plan") or {}, msg.get("timeout_s")
            )
        if op == "heartbeat":
            return self.heartbeat(msg["worker_id"])
        if op == "epoch":
            return self.report_epoch(msg["stats"])
        if op == "epoch_barrier":
            return self.epoch_barrier(
                msg["worker_id"], int(msg["epoch"]), msg.get("timeout_s"),
                split_generation=msg.get("split_generation"),
            )
        if op == "complete":
            return self.complete(msg["worker_id"], int(msg.get("exit_code", 0)))
        if op == "request_restart":
            return self.request_restart(
                msg["worker_id"], msg.get("why") or "unspecified"
            )
        if op == "unhealthy":
            return self.report_unhealthy(
                msg["worker_id"],
                int(msg.get("epoch", -1)),
                msg.get("reason") or "unspecified",
                bad_steps=msg.get("bad_steps"),
                diag=msg.get("diag"),
                hung=bool(msg.get("hung", False)),
            )
        if op == "status":
            return self.status()
        if op == "metrics":
            return {"ok": True, "text": self.metrics_text()}
        if op in ("score_plan", "lease_acquire", "lease_renew",
                  "shard_commit"):
            job = self._score_job
            if job is None:
                return {"ok": False, "error": "no score job attached"}
            if op == "score_plan":
                return job.plan_msg()
            if op == "lease_acquire":
                return job.rpc_acquire(msg["worker_id"])
            if op == "lease_renew":
                return job.rpc_renew(int(msg["shard"]), msg["lease"])
            return job.rpc_commit(
                int(msg["shard"]), msg["lease"], msg.get("manifest") or {},
                msg.get("worker_id"),
            )
        return {"ok": False, "error": f"unknown op {op!r}"}

    def shutdown(self) -> None:
        self.liveness.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class CoordinatorClient:
    """Worker-side client: one JSON-line request per short connection.

    Transient transport failures (refused connects during a coordinator
    restart, resets when the listener sheds connections mid-barrier, lost
    replies) retry with backoff under ``retry_policy`` — every op is safe
    to redeliver because the non-idempotent ones (``register``,
    ``report_epoch``, ``complete``) carry a per-logical-call dedup token
    the server replays from its response cache.  Barrier ops
    (``await_start``/``sync_plan``/``epoch_barrier``) reconnect and
    re-enter their server-side wait; the server's own deadline, measured
    from job/generation start, still governs.
    """

    def __init__(self, host: str, port: int, timeout_s: float = 600.0,
                 retry_policy: "retry_util.RetryPolicy | None" = None):
        self.addr = (host, port)
        self.timeout_s = timeout_s
        # None = resolve the process default per call (set_default_policy)
        self._retry_policy = retry_policy
        # NTP-style clock-offset estimator against the coordinator, fed
        # by every reply's srv_recv_ts/srv_ts stamps (obs/fleet.py).  A
        # relaunched worker builds a fresh client, so the estimate never
        # survives the process whose clock it describes.
        from shifu_tensorflow_tpu.obs.fleet import ClockSync

        self.clock = ClockSync()

    def clock_offset(self) -> float | None:
        """Estimated coordinator-clock minus local-clock seconds (None
        before the first stamped exchange)."""
        return self.clock.offset()

    def call(
        self, msg: dict[str, Any], timeout_s: float | str = "default"
    ) -> dict[str, Any]:
        timeout = self.timeout_s if timeout_s == "default" else timeout_s
        payload = (json.dumps(msg) + "\n").encode()

        def attempt() -> dict[str, Any]:
            faults.check("rpc.connect")
            t0 = time.time()
            with socket.create_connection(self.addr, timeout=timeout) as s:
                f = s.makefile("rwb")
                f.write(payload)
                f.flush()
                # "rpc.recv" models the reply lost AFTER the server applied
                # the op — the delivery the dedup tokens exist for
                faults.check("rpc.recv")
                line = f.readline()
                if not line:
                    raise ConnectionError("coordinator closed connection")
                if not line.endswith(b"\n"):
                    # torn mid-reply: transport failure, not a protocol error
                    raise ConnectionError("truncated coordinator reply")
                t3 = time.time()
                resp = json.loads(line)
                if isinstance(resp, dict) and "srv_ts" in resp:
                    # full NTP four-tuple: server processing time (a
                    # barrier can block for minutes) cancels; the
                    # min-delay filter inside ClockSync bounds the
                    # residual error by half the network round trip
                    self.clock.update(t0, resp.get("srv_recv_ts"),
                                      resp["srv_ts"], t3)
                    from shifu_tensorflow_tpu.obs import fleet as obs_fleet

                    obs_fleet.note_offset(self.clock.offset())
                return resp

        policy = (self._retry_policy if self._retry_policy is not None
                  else retry_util.default_policy())
        # obs span: the WHOLE logical call including server-side barrier
        # waits — "how long was this worker blocked on the coordinator"
        # is exactly the per-replica signal SPMD stall diagnosis needs
        with obs_trace.span(f"rpc.{msg.get('op', '?')}"):
            return retry_util.call(
                attempt, policy=policy, site=f"rpc.{msg.get('op', '?')}"
            )

    def register(
        self,
        worker_id: str,
        worker_index: int | None = None,
        host: str | None = None,
        jax_port: int | None = None,
        role: str = "worker",
    ) -> dict[str, Any]:
        return self.call(
            {
                "op": "register",
                "worker_id": worker_id,
                "worker_index": worker_index,
                "host": host,
                "jax_port": jax_port,
                "role": role,
                "token": uuid.uuid4().hex,
            }
        )

    def standby_wait(self, worker_id: str,
                     timeout_s: float = 10.0) -> dict[str, Any]:
        """Standby long-poll for a promotion; no socket timeout — the
        server bounds the wait by ``timeout_s`` itself."""
        return self.call(
            {"op": "standby_wait", "worker_id": worker_id,
             "timeout_s": timeout_s},
            timeout_s=None,
        )

    def resize(self, n_workers: int) -> dict[str, Any]:
        """Explicit elastic grow/shrink (admin surface; needs
        JobSpec.elastic)."""
        return self.call({"op": "resize", "n_workers": n_workers})

    def await_start(self, timeout_s: float | None = None) -> dict[str, Any]:
        # no socket timeout: the server responds by its own registration
        # deadline, which may exceed the default RPC timeout
        return self.call(
            {"op": "await_start", "timeout_s": timeout_s}, timeout_s=None
        )

    def sync_plan(self, worker_id: str, plan: dict) -> dict[str, Any]:
        # no socket timeout: the server enforces its own barrier deadline
        return self.call(
            {"op": "sync_plan", "worker_id": worker_id, "plan": plan},
            timeout_s=None,
        )

    def heartbeat(self, worker_id: str) -> dict[str, Any]:
        return self.call({"op": "heartbeat", "worker_id": worker_id})

    def report_epoch(self, stats: EpochStats) -> dict[str, Any]:
        return self.call({"op": "epoch", "stats": stats.__dict__,
                          "token": uuid.uuid4().hex})

    def epoch_barrier(self, worker_id: str, epoch: int,
                      split_generation: int | None = None) -> dict[str, Any]:
        # no socket timeout: the server enforces its own barrier deadline
        msg = {"op": "epoch_barrier", "worker_id": worker_id,
               "epoch": epoch}
        if split_generation is not None:
            # echoed per request so a lost resplit reply self-heals at
            # the next barrier (the server compares, never stores)
            msg["split_generation"] = split_generation
        return self.call(msg, timeout_s=None)

    def complete(self, worker_id: str, exit_code: int = 0) -> dict[str, Any]:
        return self.call(
            {"op": "complete", "worker_id": worker_id,
             "exit_code": exit_code, "token": uuid.uuid4().hex}
        )

    def request_restart(self, worker_id: str, why: str) -> dict[str, Any]:
        return self.call(
            {"op": "request_restart", "worker_id": worker_id, "why": why}
        )

    def report_unhealthy(
        self,
        worker_id: str,
        epoch: int,
        reason: str,
        bad_steps: list | None = None,
        diag: dict | None = None,
        hung: bool = False,
    ) -> dict[str, Any]:
        # non-idempotent (charges rollback/restart budget): the dedup
        # token keeps a retried delivery from double-charging
        return self.call(
            {
                "op": "unhealthy",
                "worker_id": worker_id,
                "epoch": epoch,
                "reason": reason,
                "bad_steps": list(bad_steps or []),
                "diag": diag or {},
                "hung": hung,
                "token": uuid.uuid4().hex,
            }
        )

    # ---- bulk scoring plane (score/) ----

    def score_plan(self) -> dict[str, Any]:
        """The attached score job's description (shards, tenants,
        output) — idempotent read."""
        return self.call({"op": "score_plan"})

    def lease_acquire(self, worker_id: str) -> dict[str, Any]:
        # non-idempotent (grants a lease, mints its token server-side):
        # the dedup token makes a redelivered acquire replay the SAME
        # grant instead of leasing a second shard to a worker that will
        # only work one
        return self.call({"op": "lease_acquire", "worker_id": worker_id,
                          "token": uuid.uuid4().hex})

    def lease_renew(self, shard: int, lease: str) -> dict[str, Any]:
        # idempotent: renewing twice extends to (about) the same
        # deadline; a refused renewal stays refused
        return self.call({"op": "lease_renew", "shard": shard,
                          "lease": lease})

    def shard_commit(self, shard: int, lease: str,
                     manifest: dict) -> dict[str, Any]:
        # non-idempotent in its counters (a redelivered winning commit
        # must not journal shard_discarded_duplicate against itself):
        # the dedup token replays the original verdict
        return self.call({"op": "shard_commit", "shard": shard,
                          "lease": lease, "manifest": manifest,
                          "worker_id": manifest.get("worker"),
                          "token": uuid.uuid4().hex})

    def status(self) -> dict[str, Any]:
        return self.call({"op": "status"})

    def metrics(self) -> str:
        """The coordinator's Prometheus text (the serve-/metrics analogue
        for the control plane)."""
        return self.call({"op": "metrics"}).get("text", "")
