"""Worker-side runtime: register → barrier → train → report → complete.

Parity surface: the reference's in-container executor chain —
``TensorflowTaskExecutor`` registering on ZK, awaiting the final cluster,
then exec'ing the Python trainer whose epoch loop pushes metrics to the
local socket server (TensorflowTaskExecutor.java:93-111,300-317,
ssgd_monitor.py:268-293).  Here the whole chain is one process: the worker
registers with the coordinator, blocks on the start barrier, streams its
shard into the Trainer, reports per-epoch stats and heartbeats in-band, and
completes with an exit code the coordinator's failure policy consumes.

Cross-process SPMD (``JobSpec.spmd``): the fleet is ONE ``jax.distributed``
job — the worker initializes the jax coordination service from the
coordinator's cluster info (chief host + reserved port), builds the global
mesh spanning every process's devices, and feeds only its local slice of
the global batch; XLA all-reduces gradients across processes.  That is the
TPU-native replacement for the reference's PS + SyncReplicasOptimizer
(ssgd_monitor.py:136-142): N workers train ONE model.

Recovery: on start the worker always tries to restore the shared
checkpoint; a relaunched worker therefore resumes at the right epoch with
its sticky shard (replaces backup wake-up, and fixes the epoch-budget gap
acknowledged at backup.py:30).  SPMD recovery is fleet-wide — the
coordinator bumps the generation, the submitter kills + relaunches every
process, and sync_plan agrees the restore epoch.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Callable

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import (
    RESTART_EXIT_CODE,
    UNHEALTHY_EXIT_CODE,
    CoordinatorClient,
)
from shifu_tensorflow_tpu.data.dataset import (
    InMemoryDataset,
    ShardStream,
    fixed_step_batches,
)
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.checkpoint import Checkpointer, NpzCheckpointer
from shifu_tensorflow_tpu.train.trainer import HealthConfig, TrainingUnhealthy
from shifu_tensorflow_tpu.utils import logs

log = logs.get("worker")


@dataclass
class WorkerConfig:
    worker_id: str
    coordinator_host: str
    coordinator_port: int
    model_config: ModelConfig
    schema: RecordSchema
    # pins this worker to a cluster slot; None lets the coordinator pick
    worker_index: int | None = None
    # "worker" | "standby": a standby registers rankless, pre-builds its
    # model/optimizer (compile warm, no data shard), heartbeats, and
    # long-polls the coordinator until a rank failure promotes it — then
    # runs the normal worker lifecycle as that rank (docs/resilience.md)
    role: str = "worker"
    batch_size: int = 100
    checkpoint_dir: str | None = None
    checkpoint_every_epochs: int = 1
    valid_rate: float | None = None  # None -> model_config.valid_set_rate
    heartbeat_interval_s: float = 0.5
    mesh_spec: str | None = None
    seed: int = 0
    dtype: str | None = None  # "float32" | "bfloat16"; None -> float32
    # cross-process SPMD membership (one model across the fleet)
    spmd: bool = False
    host: str = "127.0.0.1"  # this worker's address for peers
    # streaming input (1B-row path): stream the shard instead of loading it
    stream: bool = False
    # staged-ingest knobs (shifu.tpu.data-* keys; data/pipeline.py):
    # None/0 = auto — the per-worker autotuner sizes the dimension
    # between epochs; an explicit value pins it (data/autotune.py)
    n_readers: int | None = None
    decode_workers: int | None = None
    data_prefetch: int | None = None
    data_autotune: bool = True
    # seeded shuffle-buffer window in rows (0 = off); deterministic per
    # (seed, epoch) at any reader/decode width
    data_shuffle_rows: int = 0
    # device-infeed lookahead (conf key shifu.tpu.prefetch-depth)
    prefetch_depth: int = 2
    # batches per lax.scan dispatch (conf key shifu.tpu.scan-steps)
    scan_steps: int = 1
    # microbatches per optimizer update (conf key shifu.tpu.accum-steps)
    accum_steps: int = 1
    # keep-best metric ("" = off; conf key shifu.tpu.keep-best); the
    # chief persists its best snapshot beside the shared checkpoints
    keep_best: str = ""
    # background checkpoint writes (conf key shifu.tpu.async-checkpoint)
    async_checkpoint: bool = False
    # use the flat-file NpzCheckpointer (sidecar-manifest-verified save /
    # quarantine-and-fall-back restore) even for non-SPMD workers; SPMD
    # always uses it (orbax's collective barriers deadlock there)
    flat_checkpoint: bool = False
    # binary shard cache directory (data/cache.py); None = no caching
    cache_dir: str | None = None
    # streaming transport dtype for features (conf key
    # shifu.tpu.stream-feature-dtype): auto = bf16 unless hashing or
    # un-normalized features (no ZSCALE stats)
    stream_feature_dtype: str = "auto"
    # transient-fault retry envelope (shifu.tpu.retry-* keys) as a
    # RetryPolicy dict; None keeps the process default.  Carried in the
    # JSON transport so subprocess workers inherit the submit-side conf.
    retry: dict | None = None
    # training-health guard (shifu.tpu.health-* keys): on-device
    # isfinite checks on loss/grad-norm, EMA loss-spike detection, and
    # the wall-clock per-step hang watchdog (0 = off)
    health_check_finite: bool = True
    health_spike_factor: float = 0.0
    health_spike_min_epochs: int = 2
    health_hang_timeout_s: float = 0.0
    # observability plane (shifu.tpu.obs-* keys) as an ObsConfig dict;
    # None keeps obs off.  Carried in the JSON transport so subprocess
    # workers inherit the submit-side conf — each worker journals to
    # <journal_path>.w<index> (one writer per file, obs/journal.py)
    obs: dict | None = None

    def to_json(self) -> dict:
        """JSON transport for subprocess workers (worker_main)."""
        from dataclasses import asdict

        d = {
            k: getattr(self, k)
            for k in (
                "worker_id", "coordinator_host", "coordinator_port",
                "worker_index", "role", "batch_size", "checkpoint_dir",
                "checkpoint_every_epochs", "valid_rate",
                "heartbeat_interval_s", "mesh_spec", "seed", "dtype",
                "spmd", "host", "stream", "n_readers", "decode_workers",
                "data_prefetch", "data_autotune", "data_shuffle_rows",
                "prefetch_depth",
                "scan_steps", "accum_steps", "keep_best",
                "async_checkpoint", "flat_checkpoint", "cache_dir",
                "stream_feature_dtype",
                "retry", "health_check_finite", "health_spike_factor",
                "health_spike_min_epochs", "health_hang_timeout_s",
                "obs",
            )
        }
        d["model_config"] = dict(self.model_config.raw)
        d["schema"] = asdict(self.schema)
        return d

    @classmethod
    def from_json(cls, d: dict) -> "WorkerConfig":
        d = dict(d)
        mc = ModelConfig.from_json(d.pop("model_config") or {})
        s = d.pop("schema")
        schema = RecordSchema(
            feature_columns=tuple(s["feature_columns"]),
            target_column=s["target_column"],
            weight_column=s.get("weight_column", -1),
            delimiter=s.get("delimiter", "|"),
            means=tuple(s.get("means") or ()),
            stds=tuple(s.get("stds") or ()),
        )
        return cls(model_config=mc, schema=schema, **d)


class _HeartbeatThread(threading.Thread):
    """``generation=None`` disables the fleet-restart watch: standbys
    keep heartbeating across generation bumps — they are not collective
    participants, and their promotion reply carries whatever generation
    is current."""

    def __init__(
        self,
        client: CoordinatorClient,
        worker_id: str,
        interval_s: float,
        generation: int | None = 0,
    ):
        super().__init__(daemon=True)
        self.client = client
        self.worker_id = worker_id
        self.interval_s = interval_s
        self.generation = generation
        self.abort = threading.Event()
        self.restart = threading.Event()
        # the coordinator wrote this rank off (standby promoted into it,
        # or an elastic shrink re-split its rows away) while this
        # process was merely FLAPPED: exit cooperatively at the next
        # epoch boundary instead of training a shard someone else owns
        self.released = threading.Event()
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                resp = self.client.heartbeat(self.worker_id)
                if resp.get("abort"):
                    self.abort.set()
                    return
                if resp.get("released"):
                    self.released.set()
                    return
                if self.generation is None:
                    continue
                if int(resp.get("generation", self.generation)) != self.generation:
                    # fleet restarted without us (we may be about to be
                    # killed by the submitter; exit cooperatively first)
                    self.restart.set()
                    return
            except Exception:
                # coordinator unreachable: keep trying; the trainer decides
                # nothing — the coordinator's liveness policy decides for us
                continue

    def stop(self) -> None:
        self._stop.set()


def _stream_step_estimate(
    total_lines: int, rate: float, batch_size: int
) -> int:
    """Step count covering a hash-split stream of ``total_lines`` rows at
    split ``rate`` with overwhelming probability.

    Row→train/valid membership is per-row content hashing, so the actual
    split size is Binomial(lines, rate): mean ``lines*rate``, sd at most
    ``sqrt(lines)/2``.  Overshooting steps costs only zero-weight padding
    batches; undershooting silently drops rows — so budget mean + 8 sd.
    """
    if rate <= 0.0:
        return 0
    bound = total_lines * rate + 4.0 * math.sqrt(max(total_lines, 1))
    return max(1, int(math.ceil(min(bound, total_lines) / batch_size)))


def _health_from_cfg(cfg: WorkerConfig, lr_scale: float = 1.0,
                     skip: dict | None = None) -> HealthConfig:
    """HealthConfig from the worker's knobs plus the coordinator's
    rollback directive — one resolver shared by the normal lifecycle and
    the standby pre-build, so the two cannot drift."""
    skip = skip or {}
    return HealthConfig(
        check_finite=cfg.health_check_finite,
        spike_factor=cfg.health_spike_factor,
        spike_min_epochs=cfg.health_spike_min_epochs,
        hang_timeout_s=cfg.health_hang_timeout_s,
        lr_scale=lr_scale,
        skip_epoch=(int(skip["epoch"]) if skip.get("epoch") is not None
                    else None),
        skip_steps=tuple(int(s) for s in (skip.get("steps") or ())),
    )


def _build_trainer(cfg: WorkerConfig, model_config, health, *,
                   worker_index: int, mesh=None, topology=None):
    """The one trainer-construction site (normal lifecycle AND standby
    pre-build build through here)."""
    extra = {}
    if cfg.dtype:
        import jax.numpy as jnp

        extra["dtype"] = {"float32": jnp.float32,
                          "bfloat16": jnp.bfloat16}[cfg.dtype]
    # feature_columns must match what the export trainer will use, or
    # wide/embedding column positions (and so the param tree) diverge
    # between the trained checkpoint and the restored export model
    return make_trainer(
        model_config,
        cfg.schema.num_features,
        feature_columns=cfg.schema.feature_columns,
        mesh=mesh,
        worker_index=worker_index,
        seed=cfg.seed,
        topology=topology,
        prefetch_depth=cfg.prefetch_depth,
        scan_steps=cfg.scan_steps,
        accum_steps=cfg.accum_steps,
        keep_best=cfg.keep_best,
        health=health,
        **extra,
    )


def _standby_phase(cfg: WorkerConfig, client: CoordinatorClient):
    """Hot-standby lifecycle until promotion: register rankless, pre-build
    the model/optimizer and compile-warm the step functions (no data
    shard touched), heartbeat, and long-poll ``standby_wait``.

    Returns ``(promotion_reply, prebuilt_trainer_or_None)``; ``(None,
    None)`` when the job ends without this standby being needed.  The
    prebuild is best-effort — any failure just means the promoted rank
    builds cold, exactly like a relaunched worker.
    """
    reg = client.register(cfg.worker_id, host=cfg.host, role="standby")
    if not reg.get("ok"):
        log.error("standby registration rejected: %s", reg.get("error"))
        return None, None
    hb = _HeartbeatThread(
        client, cfg.worker_id, cfg.heartbeat_interval_s, generation=None
    )
    hb.start()
    trainer = None
    try:
        if not bool(reg.get("spmd", cfg.spmd)):
            # SPMD standbys stay un-built: the mesh spans processes that
            # only exist once the (restarted) fleet forms; their compile
            # warmth comes from the persistent compile cache instead
            try:
                mesh = None
                if cfg.mesh_spec:
                    from shifu_tensorflow_tpu.parallel.mesh import make_mesh

                    mesh = make_mesh(cfg.mesh_spec)
                trainer = _build_trainer(
                    cfg, cfg.model_config, _health_from_cfg(cfg),
                    worker_index=-1, mesh=mesh,
                )
                warmed = trainer.warm_step(
                    cfg.batch_size,
                    x_dtype=_np_feature_dtype(cfg) if cfg.stream else None,
                )
                log.info("standby %s pre-built and warmed %s",
                         cfg.worker_id, warmed)
            except Exception:
                log.exception(
                    "standby pre-build failed (%s); promotion will build "
                    "cold", cfg.worker_id)
                trainer = None
        while True:
            if hb.abort.is_set():
                return None, None
            try:
                resp = client.standby_wait(cfg.worker_id, timeout_s=10.0)
            except Exception:
                # coordinator unreachable past the retry envelope: the
                # job is gone — a standby exits quietly, it was never a
                # rank anyone is waiting on
                log.exception("standby %s lost the coordinator; exiting",
                              cfg.worker_id)
                return None, None
            if resp.get("promoted"):
                log.warning(
                    "standby %s promoted into rank %s (generation %s)",
                    cfg.worker_id, resp.get("worker_index"),
                    resp.get("generation"),
                )
                return resp, trainer
            if not resp.get("ok"):
                # terminal job state (or we were never admitted)
                return None, None
    finally:
        hb.stop()


def run_worker(cfg: WorkerConfig, *,
               fail_at_epoch: int | None = None) -> int:
    """Full worker lifecycle; returns the exit code it reported.

    ``fail_at_epoch`` is the built-in fault-injection hook (the reference
    only had a commented-out kill-PS-after-80s hack,
    CommonUtils.java:265-273): the worker aborts mid-job at that epoch.

    ``cfg.role == "standby"`` prepends the hot-standby phase: register
    rankless, pre-build + compile-warm, wait for a promotion — then run
    this very lifecycle as the promoted rank (the re-registration is
    sticky: the coordinator moved the standby's record into the dead
    rank's slot, so the register below returns that rank's shard, epoch
    state, and health directive).
    """
    from shifu_tensorflow_tpu.parallel import distributed as dist

    logs.set_worker(cfg.worker_id)
    if cfg.retry is not None:
        # subprocess workers inherit the submit-side retry envelope; the
        # fs backends and checkpointer resolve the default lazily per call
        from shifu_tensorflow_tpu.utils import retry as retry_util

        retry_util.set_default_policy(
            retry_util.RetryPolicy.from_dict(cfg.retry)
        )
    client = CoordinatorClient(cfg.coordinator_host, cfg.coordinator_port)
    prebuilt = None
    promoted_from_standby = False
    if cfg.role == "standby":
        promoted_from_standby = True
        promo, prebuilt = _standby_phase(cfg, client)
        if promo is None:
            # never promoted: the job ended (or refused us) — a clean,
            # budget-free exit the coordinator logs as standby_exit
            try:
                client.complete(cfg.worker_id, 0)
            except Exception:
                pass
            return 0
        import dataclasses as _dc

        # fall into the normal lifecycle AS the promoted rank: the
        # sticky re-registration below returns the rank's shard/state
        cfg = _dc.replace(cfg, role="worker",
                          worker_index=int(promo["worker_index"]))
    # reserve a port for the jax coordination service up front: only the
    # chief's is used, but index assignment happens at registration.  The
    # reservation is HELD (socket open) until just before initialize binds
    # it — round 2's flaky recovery traced to this port being stolen in the
    # registration window under load.
    port_hold = dist.ReservedPort(cfg.host) if cfg.spmd else None
    reg = client.register(
        cfg.worker_id, cfg.worker_index, host=cfg.host,
        jax_port=port_hold.port if port_hold else None,
    )
    if not reg.get("ok"):
        if port_hold is not None:
            port_hold.release()
        log.error("registration rejected: %s", reg.get("error"))
        return 1  # never registered; the coordinator doesn't know us
    worker_index = reg["worker_index"]
    private_tracer = None
    if cfg.obs:
        # installed AFTER registration so the journal file carries the
        # ASSIGNED index (a pinned cfg.worker_index may be None); the
        # trainer picks the tracer up at construction below
        from shifu_tensorflow_tpu.obs import ObsConfig, install_obs
        from shifu_tensorflow_tpu.obs import journal as _obs_journal
        from shifu_tensorflow_tpu.obs import trace as _obs_trace

        obs_cfg = ObsConfig.from_json(cfg.obs)
        if _obs_journal.active() is None and _obs_trace.active() is None:
            # subprocess worker: this process is ours to instrument.
            # The job correlation id rode the register reply, so every
            # worker journals the id the coordinator minted — one merged
            # journal, one job key across all planes.
            install_obs(obs_cfg, worker_index=worker_index, plane="train",
                        job=reg.get("job"))
        elif obs_cfg.enabled:
            # thread launcher: we SHARE the submitter's process, whose
            # journal/tracer are already installed — replacing them
            # would misattribute coordinator events and leak the open
            # journal.  Events flow into the shared journal (explicit
            # worker/plane fields keep attribution right); the step
            # phases get a PRIVATE per-worker tracer below so
            # take_summary() in one worker thread cannot drain
            # another's epoch.
            private_tracer = _obs_trace.Tracer(
                worker_index=worker_index,
                sample_every=obs_cfg.trace_sample,
            )
        _obs_journal.emit("worker_start", plane="train",
                          worker=worker_index,
                          worker_id=cfg.worker_id,
                          generation=int(reg.get("generation", 0)),
                          promoted=promoted_from_standby)
        if promoted_from_standby:
            _obs_journal.emit("standby_takeover", plane="train",
                              worker=worker_index,
                              worker_id=cfg.worker_id,
                              prebuilt=prebuilt is not None,
                              generation=int(reg.get("generation", 0)))
    shard_paths = reg["shard"]
    epochs = reg.get("epochs") or cfg.model_config.num_train_epochs
    sync_epochs = bool(reg.get("sync_epochs", False))
    spmd = bool(reg.get("spmd", cfg.spmd))
    generation = int(reg.get("generation", 0))
    # coordinator rollback directive: after a health rollback every worker
    # trains at the backed-off LR and skips the offending batch window —
    # identical values fleet-wide (they rode the same register reply)
    directive = reg.get("health") or {}
    lr_scale = float(directive.get("lr_scale") or 1.0)
    skip = directive.get("skip") or {}
    model_config = cfg.model_config
    if lr_scale != 1.0:
        import dataclasses as _dc

        p = model_config.params
        model_config = _dc.replace(
            model_config,
            params=_dc.replace(p, learning_rate=p.learning_rate * lr_scale),
        )
        log.warning(
            "health rollback directive: learning rate scaled x%g -> %g "
            "(rollback %s)", lr_scale,
            model_config.params.learning_rate, directive.get("rollbacks"),
        )
    health = _health_from_cfg(cfg, lr_scale=lr_scale, skip=skip)

    hb = _HeartbeatThread(
        client, cfg.worker_id, cfg.heartbeat_interval_s, generation
    )
    hb.start()
    exit_code = 0
    checkpointer = None
    trainer = None
    try:
        started = client.await_start()
        if not started.get("ok"):
            raise _JobAborted()
        valid_rate = (
            cfg.valid_rate
            if cfg.valid_rate is not None
            else cfg.model_config.valid_set_rate
        )

        # the declared fleet mesh rode the register reply (spec + THIS
        # rank's row-major coordinate — a promoted standby inherits the
        # dead rank's coordinate with its index); a locally configured
        # spec still wins so single-process runs need no coordinator
        mesh_info = reg.get("mesh") or {}
        mesh_spec = cfg.mesh_spec or mesh_info.get("spec")
        topology = None
        mesh = None
        if spmd:
            topology = dist.ProcessTopology.from_cluster_info(
                started.get("cluster") or {}, worker_index,
                local_host=cfg.host,
            )
            if port_hold is not None:
                port_hold.release()  # chief: initialize rebinds it NOW
            try:
                dist.initialize(topology)
            except Exception:
                # canonical cause: the chief's port was stolen anyway, or a
                # peer died mid-bring-up.  A fresh generation (fresh port,
                # full re-registration) cures both — request ONE budgeted
                # fleet restart attributed to this root cause instead of
                # dying opaquely and making the coordinator untangle the
                # cascade.
                log.exception(
                    "jax.distributed.initialize failed (worker_index=%s); "
                    "requesting fleet restart", worker_index,
                )
                try:
                    client.request_restart(
                        cfg.worker_id, "jax.distributed.initialize failed"
                    )
                except Exception:
                    pass
                raise _FleetRestart()
            mesh = dist.global_mesh(mesh_spec or "data:-1")
        elif mesh_spec:
            from shifu_tensorflow_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(mesh_spec)
        if mesh is not None:
            # ONE mesh event per worker start: the resolved layout (not
            # the spec string — `-1` axes are solved by now), this
            # rank's coordinate when the coordinator assigned one, and
            # the fingerprint artifacts stamp — `obs summary` renders it
            from shifu_tensorflow_tpu.obs import journal as _obs_journal
            from shifu_tensorflow_tpu.parallel.mesh import (
                mesh_shape_fingerprint,
            )

            _obs_journal.emit(
                "mesh", plane="train", worker=worker_index,
                shape={n: int(s) for n, s in mesh.shape.items()},
                coord=mesh_info.get("coord"),
                fingerprint=mesh_shape_fingerprint(mesh),
                devices=int(mesh.devices.size),
            )

        if (prebuilt is not None and not spmd and lr_scale == 1.0
                and not skip):
            # promoted standby, clean directive: the pre-built trainer's
            # construction arguments are identical to what _build_trainer
            # would produce here (same cfg, same health resolver), so the
            # warm executables carry straight into the takeover.  A
            # rollback directive (scaled LR / skip window) changes the
            # construction inputs — build fresh then.
            trainer = prebuilt
            trainer.worker_index = worker_index
            if trainer.health_guard is not None:
                trainer.health_guard.worker_index = worker_index
            # the standby built before install_obs ran: pick the plane up
            # now, exactly like construction would have
            from shifu_tensorflow_tpu.obs import trace as _obs_trace

            trainer.tracer = _obs_trace.active()
            from shifu_tensorflow_tpu.obs import slo as _obs_slo

            trainer.slo = _obs_slo.active()
        else:
            trainer = _build_trainer(
                cfg, model_config, health,
                worker_index=worker_index, mesh=mesh, topology=topology,
            )
        if private_tracer is not None:
            trainer.tracer = private_tracer
        if trainer.health_guard is not None:
            # hang watchdog → coordinated recovery: the wedged training
            # thread cannot raise, so the watchdog thread reports the
            # hang; the coordinator rolls the fleet back (SPMD: the
            # submitter SIGKILLs this very process on the generation
            # bump; non-SPMD: the submitter kills it via pending_kills)
            def _on_hang(reason: str, diag: dict) -> None:
                try:
                    client.report_unhealthy(
                        cfg.worker_id, diag.get("epoch", -1), reason,
                        diag=diag, hung=True,
                    )
                except Exception:
                    log.exception("could not report hung step")

            trainer.health_guard.on_hang = _on_hang

        if cfg.checkpoint_dir:
            # SPMD uses the flat-file checkpointer: orbax's internal
            # cross-process barriers deadlock under chief-writes/all-read.
            # flat_checkpoint opts non-SPMD workers into it too, for the
            # manifest-verified save/restore chain.
            if spmd or cfg.flat_checkpoint:
                checkpointer = NpzCheckpointer(
                    cfg.checkpoint_dir,
                    every_epochs=cfg.checkpoint_every_epochs,
                    async_save=cfg.async_checkpoint,
                )
            else:
                checkpointer = Checkpointer(
                    cfg.checkpoint_dir,
                    every_epochs=cfg.checkpoint_every_epochs,
                )

        if spmd:
            exit_code = _run_spmd_training(
                cfg, client, trainer, hb, checkpointer,
                worker_index=worker_index,
                shard_paths=shard_paths,
                epochs=epochs,
                valid_rate=valid_rate,
                fail_at_epoch=fail_at_epoch,
                shard_lines=reg.get("shard_lines"),
                sync_epochs=sync_epochs,
            )
        else:
            exit_code = _run_local_training(
                cfg, client, trainer, hb, checkpointer,
                worker_index=worker_index,
                shard_paths=shard_paths,
                epochs=epochs,
                valid_rate=valid_rate,
                sync_epochs=sync_epochs,
                fail_at_epoch=fail_at_epoch,
            )
    except TrainingUnhealthy as e:
        # divergence detected at epoch end, BEFORE the diverged state was
        # checkpointed or reported: hand the coordinator the evidence and
        # let it arbitrate one fleet-wide rollback
        log.warning(
            "health guard tripped (worker_index=%s, epoch %d): %s",
            worker_index, e.epoch, e.reason,
        )
        try:
            resp = client.report_unhealthy(
                cfg.worker_id, e.epoch, e.reason,
                bad_steps=list(e.bad_steps), diag=e.diag,
            )
        except Exception:
            log.exception("could not report unhealthy state")
            resp = {}
        if resp.get("fleet"):
            exit_code = RESTART_EXIT_CODE
        elif resp.get("ok"):
            exit_code = UNHEALTHY_EXIT_CODE
        else:
            exit_code = 42  # budget gone / job failed: cooperative abort
    except _InjectedFault:
        log.warning("injected fault fired (worker_index=%s, "
                    "fail_at_epoch=%s)", worker_index, fail_at_epoch)
        exit_code = 43
    except _FleetRestart:
        log.info("exiting for fleet restart (worker_index=%s)", worker_index)
        exit_code = RESTART_EXIT_CODE
    except _Released:
        # elastic resize released this rank: a clean exit, not a failure
        log.info("released by elastic resize (worker_index=%s)",
                 worker_index)
        exit_code = 0
    except _JobAborted:
        log.warning("job aborted by coordinator (worker_index=%s)",
                    worker_index)
        exit_code = 42
    except Exception:
        # the per-worker log file (submitter) must carry the root cause —
        # round 2's flaky recovery was undiagnosable because this path
        # swallowed the traceback
        log.exception("worker failed (worker_index=%s)", worker_index)
        exit_code = 1
    finally:
        if port_hold is not None:
            port_hold.release()
        # stop the hang watchdog FIRST: left armed, it could fire a
        # spurious unhealthy report for a worker that is already exiting
        if trainer is not None and trainer.health_guard is not None:
            try:
                trainer.health_guard.close()
            except Exception:
                pass
        # always release the checkpoint manager: leaked orbax async writer
        # threads abort the interpreter at teardown
        if checkpointer is not None:
            try:
                checkpointer.close()
            except Exception:
                pass
        hb.stop()
        try:
            client.complete(cfg.worker_id, exit_code)
        except Exception:
            pass
        from shifu_tensorflow_tpu.obs import journal as _obs_journal

        _obs_journal.emit("worker_exit", plane="train",
                          worker=worker_index,
                          worker_id=cfg.worker_id, exit_code=exit_code)
    return exit_code


class _FleetStopSignal:
    """Adapter between the coordinator's fleet early-stop decision and the
    fit loops' ``early_stop`` hook: the epoch-barrier reply fills it in
    (same value for every worker at the same barrier), and the loop then
    breaks through its normal path — AFTER the epoch's checkpoint save,
    with ``trainer.stop_reason`` recorded — instead of via an exception
    that would skip both."""

    def __init__(self):
        self.stop_after: int | None = None
        self.reason: str | None = None

    def should_stop(self, stats) -> str | None:
        if self.stop_after is not None and stats.current_epoch >= self.stop_after:
            return self.reason or "fleet early stop"
        return None


class _ShardState:
    """Mutable view of this worker's shard assignment: the streaming
    epoch factories read ``paths`` per epoch, so an elastic re-split
    delivered at the epoch barrier takes effect at the very next epoch
    without restarting the worker.  ``split_generation`` is echoed on
    every barrier call — the coordinator compares (never stores), so a
    lost resplit reply just redelivers at the next barrier."""

    def __init__(self, paths):
        self.paths = list(paths)
        self.split_generation = 0

    def apply(self, directive: dict) -> None:
        self.paths[:] = list(directive.get("shard") or self.paths)
        self.split_generation = int(directive.get("split_generation", 0))


def _epoch_callback(
    cfg: WorkerConfig,
    client: CoordinatorClient,
    hb: _HeartbeatThread,
    *,
    sync_epochs: bool,
    fail_at_epoch: int | None,
    fleet_stop: "_FleetStopSignal | None" = None,
    shard_state: "_ShardState | None" = None,
) -> Callable:
    def on_epoch(stats) -> None:
        if hb.abort.is_set():
            raise _JobAborted()
        if hb.restart.is_set():
            raise _FleetRestart()
        if hb.released.is_set():
            # heartbeat-borne release (the barrier-borne one below only
            # reaches sync_epochs fleets)
            raise _Released()
        if fail_at_epoch is not None and stats.current_epoch >= fail_at_epoch:
            raise _InjectedFault()
        client.report_epoch(stats)
        if sync_epochs:
            resp = client.epoch_barrier(
                cfg.worker_id, stats.current_epoch,
                split_generation=(shard_state.split_generation
                                  if shard_state is not None else None),
            )
            if resp.get("abort"):
                raise _JobAborted()
            if resp.get("released"):
                # resize shrink: this rank left the membership — stop
                # cleanly instead of training a shard someone else owns
                raise _Released()
            if not resp.get("ok"):
                raise RuntimeError(resp.get("error", "epoch barrier failed"))
            directive = resp.get("resplit")
            if directive and shard_state is not None:
                shard_state.apply(directive)
                log.warning(
                    "elastic re-split applied (split generation %d): "
                    "%d path(s); takes effect next epoch",
                    shard_state.split_generation, len(shard_state.paths),
                )
                from shifu_tensorflow_tpu.obs import journal as _obs_journal

                _obs_journal.emit(
                    "resplit_applied", plane="train",
                    worker=stats.worker_index,
                    split_generation=shard_state.split_generation,
                    n_paths=len(shard_state.paths),
                    n_workers=directive.get("n_workers"),
                )
            if fleet_stop is not None and "stop_after_epoch" in resp:
                fleet_stop.stop_after = int(resp["stop_after_epoch"])
                fleet_stop.reason = resp.get("stop_reason")

    return on_epoch


def _run_local_training(
    cfg, client, trainer, hb, checkpointer, *,
    worker_index, shard_paths, epochs, valid_rate, sync_epochs,
    fail_at_epoch,
) -> int:
    """Independent-model path (non-SPMD): each worker trains on its shard;
    only the chief's checkpoint is exported.

    The shard lives in a mutable _ShardState: an elastic re-split
    delivered at the epoch barrier re-points the STREAMING epoch
    factories at the new shard from the next epoch on (the in-memory
    path loaded its data up front — it picks a re-split up on relaunch,
    its coordinator record already carries the new shard)."""
    shard_state = _ShardState(shard_paths)
    fleet_stop = _FleetStopSignal() if sync_epochs else None
    on_epoch = _epoch_callback(
        cfg, client, hb, sync_epochs=sync_epochs,
        fail_at_epoch=fail_at_epoch, fleet_stop=fleet_stop,
        shard_state=shard_state,
    )
    start_epoch = 0
    if checkpointer is not None:
        start_epoch = trainer.restore(checkpointer)
    save_ckpt = checkpointer if worker_index == 0 else None

    if cfg.stream:
        batch_size = trainer.align_batch_size(cfg.batch_size)
        widths, stats_sink = _ingest_setup(cfg, trainer)
        trainer.fit_stream(
            lambda epoch: ShardStream(
                list(shard_state.paths), cfg.schema, batch_size,
                valid_rate=valid_rate, emit="train", salt=cfg.seed,
                cache_dir=cfg.cache_dir,
                feature_dtype=_feature_dtype_for(cfg),
                shuffle_rows=cfg.data_shuffle_rows,
                shuffle_seed=cfg.seed + epoch,
                stats_sink=stats_sink, **widths(),
            ),
            (lambda: ShardStream(
                list(shard_state.paths), cfg.schema, batch_size,
                valid_rate=valid_rate, emit="valid", salt=cfg.seed,
                cache_dir=cfg.cache_dir,
                feature_dtype=_feature_dtype_for(cfg),
                **widths(),
            )) if valid_rate > 0 else None,
            epochs=epochs,
            on_epoch=on_epoch,
            checkpointer=save_ckpt,
            start_epoch=start_epoch,
            early_stop=fleet_stop,
        )
    else:
        dataset = InMemoryDataset.load(
            shard_paths, cfg.schema, valid_rate, salt=cfg.seed
        )
        trainer.fit(
            dataset,
            epochs=epochs,
            batch_size=cfg.batch_size,
            on_epoch=on_epoch,
            checkpointer=save_ckpt,
            start_epoch=start_epoch,
            early_stop=fleet_stop,
        )
    if save_ckpt is not None:
        # surface a failed background write of the FINAL checkpoint here,
        # on the success path — run_worker's cleanup close() swallows
        # exceptions, so without this the job would report success with
        # the checkpoint missing
        save_ckpt.wait()
    return 0


def _ingest_setup(cfg, trainer):
    """Resolve this worker's staged-ingest knobs (shifu.tpu.data-*) and
    install the per-worker autotuner on its trainer — the shared wiring
    helper (data/autotune.install_ingest_autotuner) run_single uses too,
    so fleet and single-process paths cannot drift."""
    from shifu_tensorflow_tpu.data.autotune import install_ingest_autotuner

    return install_ingest_autotuner(
        trainer, cfg.n_readers, cfg.decode_workers, cfg.data_prefetch,
        autotune=cfg.data_autotune, fallback_prefetch=cfg.prefetch_depth,
    )


def _np_feature_dtype(cfg):
    from shifu_tensorflow_tpu.data.cache import feature_np_dtype

    return feature_np_dtype(_feature_dtype_for(cfg))


def _feature_dtype_for(cfg) -> str:
    """Streaming transport dtype — bf16 when safe (compact transfer, the
    jitted step widens on device), float32 when any column feeds a hash or
    the schema carries no ZSCALE stats (raw-magnitude features would lose
    precision); see data/dataset.py resolve_stream_feature_dtype."""
    from shifu_tensorflow_tpu.data.dataset import resolve_stream_feature_dtype

    return resolve_stream_feature_dtype(
        cfg.stream_feature_dtype,
        uses_feature_hashing=cfg.model_config.params.uses_feature_hashing,
        has_normalization_stats=bool(cfg.schema.means),
    )


def _run_spmd_training(
    cfg, client, trainer, hb, checkpointer, *,
    worker_index, shard_paths, epochs, valid_rate, fail_at_epoch,
    shard_lines=None, sync_epochs=False,
) -> int:
    """One-model path: this process is one SPMD participant.  Every process
    must execute identical step sequences, so the fleet agrees per-epoch
    step counts and the restore epoch through the coordinator's sync_plan
    barrier before training starts.

    ``sync_epochs`` engages the coordinator's per-epoch barrier here too:
    SPMD collectives already keep steps in lockstep, but fleet-level
    per-epoch DECISIONS (early stopping) need a rendezvous where every
    process sees the same answer at the same epoch — without it, a worker
    whose report completed the quorum could stop while a peer that
    reported earlier has already entered the next epoch's collectives."""
    local_batch = trainer.align_batch_size(cfg.batch_size)
    num_features = cfg.schema.num_features

    counted_lines = None
    if cfg.stream:
        # the register reply carries the coordinator-cached count (seeded at
        # submit or from a previous launch's report) — a relaunched fleet
        # must not re-read a 1B-row shard just to size its epochs
        lines = shard_lines
        if lines is None:
            from shifu_tensorflow_tpu.data.splitter import total_line_count

            lines = counted_lines = total_line_count(shard_paths)
        train_steps = _stream_step_estimate(
            lines, 1.0 - valid_rate, local_batch
        )
        valid_steps = _stream_step_estimate(lines, valid_rate, local_batch)
        dataset = None
    else:
        dataset = InMemoryDataset.load(
            shard_paths, cfg.schema, valid_rate, salt=cfg.seed
        )
        train_steps = dataset.steps_per_epoch(local_batch)
        valid_steps = dataset.valid_steps(local_batch)

    # report only VERIFIED generations into the fleet agreement: the
    # coordinator's min-over-workers must land on an epoch every worker
    # can actually restore — a corrupt-but-present generation reported
    # here would wedge the whole fleet on an unrestorable point.
    # Upgrade path: a checkpoint dir written before manifests existed has
    # restorable-but-unverifiable (legacy) generations; discarding hours
    # of progress over a missing sidecar would be worse than trusting the
    # npz-parse guard, so fall back to latest_epoch() (which itself
    # quarantines cheap-corrupt generations) when nothing is verified.
    latest = None
    if checkpointer is not None:
        latest = getattr(
            checkpointer, "latest_verified_epoch",
            lambda: None,
        )()
        if latest is None:
            latest = checkpointer.latest_epoch()
    plan_payload = {
        "train_steps": train_steps,
        "valid_steps": valid_steps,
        "ckpt_epoch": -1 if latest is None else int(latest),
    }
    if counted_lines is not None:
        plan_payload["shard_lines"] = counted_lines
    plan = client.sync_plan(cfg.worker_id, plan_payload)
    if plan.get("restart"):
        raise _FleetRestart()
    if not plan.get("ok"):
        if plan.get("abort"):
            raise _JobAborted()
        raise RuntimeError(plan.get("error", "sync_plan failed"))
    train_steps = int(plan["train_steps"])
    valid_steps = int(plan["valid_steps"])
    agreed_epoch = int(plan.get("ckpt_epoch", -1))

    start_epoch = 0
    if checkpointer is not None and agreed_epoch >= 0:
        state, start_epoch = checkpointer.restore_epoch(
            agreed_epoch, trainer.state
        )
        trainer.state = state
    if cfg.keep_best and checkpointer is not None:
        # resumed fleets compete against the TRUE best, not
        # best-since-restart (trainer.restore does this for the non-SPMD
        # path; SPMD restores through restore_epoch).  Unconditional on
        # the agreed epoch: a relaunch BEFORE the first checkpoint
        # (agreed_epoch -1) may still have a persisted best from the
        # previous generation's epoch 0 — restarting the race would let
        # a worse post-relaunch epoch overwrite it.
        trainer._restore_best(checkpointer.directory)

    def _warn_dropped(rows: int) -> None:
        log.warning(
            "fixed-step epoch dropped %d surplus rows (agreed %d steps)",
            rows, train_steps,
        )

    if cfg.stream:
        x_dtype = _np_feature_dtype(cfg)
        widths, stats_sink = _ingest_setup(cfg, trainer)

        def make_train(epoch: int):
            return fixed_step_batches(
                ShardStream(
                    shard_paths, cfg.schema, local_batch,
                    valid_rate=valid_rate, emit="train", salt=cfg.seed,
                    cache_dir=cfg.cache_dir,
                    feature_dtype=_feature_dtype_for(cfg),
                    shuffle_rows=cfg.data_shuffle_rows,
                    shuffle_seed=cfg.seed + epoch,
                    stats_sink=stats_sink, **widths(),
                ),
                local_batch, train_steps, num_features,
                on_dropped=_warn_dropped, x_dtype=x_dtype,
            )

        def make_valid():
            return fixed_step_batches(
                ShardStream(
                    shard_paths, cfg.schema, local_batch,
                    valid_rate=valid_rate, emit="valid", salt=cfg.seed,
                    cache_dir=cfg.cache_dir,
                    feature_dtype=_feature_dtype_for(cfg),
                    **widths(),
                ),
                local_batch, valid_steps, num_features, x_dtype=x_dtype,
            )
    else:
        def make_train(epoch: int):
            return dataset.train_batches_fixed(
                local_batch, train_steps, epoch=epoch
            )

        def make_valid():
            return dataset.valid_batches_fixed(local_batch, valid_steps)

    fleet_stop = _FleetStopSignal() if sync_epochs else None
    on_epoch = _epoch_callback(
        cfg, client, hb, sync_epochs=sync_epochs,
        fail_at_epoch=fail_at_epoch, fleet_stop=fleet_stop,
    )
    trainer.fit_stream(
        make_train,
        make_valid if valid_steps > 0 else None,
        epochs=epochs,
        on_epoch=on_epoch,
        checkpointer=checkpointer if worker_index == 0 else None,
        start_epoch=start_epoch,
        early_stop=fleet_stop,
    )
    if worker_index == 0 and checkpointer is not None:
        checkpointer.wait()  # see _run_local_training: no silent ckpt loss
    return 0


class _InjectedFault(RuntimeError):
    pass


class _JobAborted(RuntimeError):
    pass


class _FleetRestart(RuntimeError):
    pass


class _Released(RuntimeError):
    """Elastic resize removed this rank from the membership."""
