"""Worker-side runtime: register → barrier → train → report → complete.

Parity surface: the reference's in-container executor chain —
``TensorflowTaskExecutor`` registering on ZK, awaiting the final cluster,
then exec'ing the Python trainer whose epoch loop pushes metrics to the
local socket server (TensorflowTaskExecutor.java:93-111,300-317,
ssgd_monitor.py:268-293).  Here the whole chain is one process: the worker
registers with the coordinator, blocks on the start barrier, streams its
shard into the Trainer, reports per-epoch stats and heartbeats in-band, and
completes with an exit code the coordinator's failure policy consumes.

Recovery: on start the worker always tries to restore the shared
checkpoint; a relaunched worker therefore resumes at the right epoch with
its sticky shard (replaces backup wake-up, and fixes the epoch-budget gap
acknowledged at backup.py:30).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable

from shifu_tensorflow_tpu.config.model_config import ModelConfig
from shifu_tensorflow_tpu.coordinator.coordinator import CoordinatorClient
from shifu_tensorflow_tpu.data.dataset import InMemoryDataset
from shifu_tensorflow_tpu.data.reader import RecordSchema
from shifu_tensorflow_tpu.train import make_trainer
from shifu_tensorflow_tpu.train.checkpoint import Checkpointer


@dataclass
class WorkerConfig:
    worker_id: str
    coordinator_host: str
    coordinator_port: int
    model_config: ModelConfig
    schema: RecordSchema
    # pins this worker to a cluster slot; None lets the coordinator pick
    worker_index: int | None = None
    batch_size: int = 100
    checkpoint_dir: str | None = None
    checkpoint_every_epochs: int = 1
    valid_rate: float | None = None  # None -> model_config.valid_set_rate
    heartbeat_interval_s: float = 0.5
    mesh_spec: str | None = None
    seed: int = 0
    dtype: str | None = None  # "float32" | "bfloat16"; None -> float32


class _HeartbeatThread(threading.Thread):
    def __init__(self, client: CoordinatorClient, worker_id: str, interval_s: float):
        super().__init__(daemon=True)
        self.client = client
        self.worker_id = worker_id
        self.interval_s = interval_s
        self.abort = threading.Event()
        self._stop = threading.Event()

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                resp = self.client.heartbeat(self.worker_id)
                if resp.get("abort"):
                    self.abort.set()
                    return
            except Exception:
                # coordinator unreachable: keep trying; the trainer decides
                # nothing — the coordinator's liveness policy decides for us
                continue

    def stop(self) -> None:
        self._stop.set()


def run_worker(cfg: WorkerConfig, *,
               fail_at_epoch: int | None = None) -> int:
    """Full worker lifecycle; returns the exit code it reported.

    ``fail_at_epoch`` is the built-in fault-injection hook (the reference
    only had a commented-out kill-PS-after-80s hack,
    CommonUtils.java:265-273): the worker aborts mid-job at that epoch.
    """
    client = CoordinatorClient(cfg.coordinator_host, cfg.coordinator_port)
    reg = client.register(cfg.worker_id, cfg.worker_index)
    if not reg.get("ok"):
        return 1  # never registered; the coordinator doesn't know us
    worker_index = reg["worker_index"]
    shard_paths = reg["shard"]
    epochs = reg.get("epochs") or cfg.model_config.num_train_epochs
    sync_epochs = bool(reg.get("sync_epochs", False))

    hb = _HeartbeatThread(client, cfg.worker_id, cfg.heartbeat_interval_s)
    hb.start()
    exit_code = 0
    checkpointer = None
    try:
        started = client.await_start()
        if not started.get("ok"):
            raise _JobAborted()
        valid_rate = (
            cfg.valid_rate
            if cfg.valid_rate is not None
            else cfg.model_config.valid_set_rate
        )
        dataset = InMemoryDataset.load(shard_paths, cfg.schema, valid_rate)

        mesh = None
        if cfg.mesh_spec:
            from shifu_tensorflow_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(cfg.mesh_spec)
        extra = {}
        if cfg.dtype:
            import jax.numpy as jnp

            extra["dtype"] = {"float32": jnp.float32,
                              "bfloat16": jnp.bfloat16}[cfg.dtype]
        # feature_columns must match what the export trainer will use, or
        # wide/embedding column positions (and so the param tree) diverge
        # between the trained checkpoint and the restored export model
        trainer = make_trainer(
            cfg.model_config,
            cfg.schema.num_features,
            feature_columns=cfg.schema.feature_columns,
            mesh=mesh,
            worker_index=worker_index,
            seed=cfg.seed,
            **extra,
        )

        start_epoch = 0
        if cfg.checkpoint_dir:
            checkpointer = Checkpointer(
                cfg.checkpoint_dir, every_epochs=cfg.checkpoint_every_epochs
            )
            start_epoch = trainer.restore(checkpointer)

        def on_epoch(stats) -> None:
            if hb.abort.is_set():
                raise _JobAborted()
            if fail_at_epoch is not None and stats.current_epoch >= fail_at_epoch:
                raise _InjectedFault()
            client.report_epoch(stats)
            if sync_epochs:
                resp = client.epoch_barrier(cfg.worker_id, stats.current_epoch)
                if resp.get("abort"):
                    raise _JobAborted()
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "epoch barrier failed"))

        trainer.fit(
            dataset,
            epochs=epochs,
            batch_size=cfg.batch_size,
            on_epoch=on_epoch,
            checkpointer=checkpointer if worker_index == 0 else None,
            start_epoch=start_epoch,
        )
    except _InjectedFault:
        exit_code = 43
    except _JobAborted:
        exit_code = 42
    except Exception:
        exit_code = 1
    finally:
        # always release the orbax manager: leaked async writer threads
        # abort the interpreter at teardown
        if checkpointer is not None:
            try:
                checkpointer.close()
            except Exception:
                pass
        hb.stop()
        try:
            client.complete(cfg.worker_id, exit_code)
        except Exception:
            pass
    return exit_code


class _InjectedFault(RuntimeError):
    pass


class _JobAborted(RuntimeError):
    pass
