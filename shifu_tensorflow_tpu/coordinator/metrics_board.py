"""Per-epoch metrics aggregation and the console board.

Parity surface: the reference's 4-hop metrics plane — worker Python →
localhost socket → per-container Java parser → ZK znode → AM aggregation —
ends in ``doStatistic``: when every worker has reported an epoch, compute
mean train/valid error, mean epoch/valid wall times, sort out the slowest
worker, and append a line to an HDFS "console board" file the client tails
(SocketServer.java:56-95, TensorflowSession.java:515-549,595-626,
CommonUtils.ClientConsoleBoard:426-458).

Design fix over the reference (SURVEY.md §7.3 last item): the reference
drops stale epochs and races across workers' epoch boundaries; here records
are keyed by (epoch, worker_index) so late arrivals land in their own epoch
bucket and an epoch is published exactly once, when its quorum completes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

from shifu_tensorflow_tpu.train.trainer import EpochStats
from shifu_tensorflow_tpu.utils import fs

# LatencyHistogram lived here through PR 3, moved to obs/registry.py in
# PR 4 (one metrics-primitive home behind every scrape surface), and
# the compatibility re-export was dropped in PR 9 — import it from
# shifu_tensorflow_tpu.obs.registry.


@dataclass
class EpochSummary:
    epoch: int
    n_workers: int
    mean_training_loss: float
    mean_valid_loss: float
    mean_training_time_s: float
    mean_valid_time_s: float
    slowest_worker: int
    slowest_time_s: float
    ks: float = 0.0
    auc: float = 0.0

    def board_line(self) -> str:
        return (
            f"epoch {self.epoch}: avg train err {self.mean_training_loss:.6f}, "
            f"avg valid err {self.mean_valid_loss:.6f}, "
            f"avg epoch time {self.mean_training_time_s:.2f}s, "
            f"avg valid time {self.mean_valid_time_s:.2f}s, "
            f"ks {self.ks:.4f}, auc {self.auc:.4f}, "
            f"slowest worker {self.slowest_worker} "
            f"({self.slowest_time_s:.2f}s)\n"
        )


class EpochAggregator:
    def __init__(
        self,
        n_workers: int,
        board_path: str | None = None,
        on_epoch_complete: Callable[[EpochSummary], None] | None = None,
    ):
        self.n_workers = n_workers
        self.board_path = board_path
        self.on_epoch_complete = on_epoch_complete
        self._records: dict[int, dict[int, EpochStats]] = {}
        self._published: set[int] = set()
        self._lock = threading.Lock()
        self.summaries: list[EpochSummary] = []

    def set_expected(self, n_workers: int) -> None:
        """Elastic membership change (coordinator shrink/resize): later
        epochs reach quorum at the NEW width.  Epochs already holding
        more reports than the new width flush on the next completing
        report via the partial-quorum path."""
        with self._lock:
            self.n_workers = int(n_workers)

    def report(self, stats: EpochStats) -> EpochSummary | None:
        """Record one worker's epoch stats; returns the summary if this
        report completes the epoch's quorum.  When an epoch completes, any
        earlier epoch still unpublished is flushed with partial quorum
        first — a restarted worker that resumed past it would otherwise
        leave a permanent hole (its skipped epochs can never reach
        quorum)."""
        with self._lock:
            epoch = stats.current_epoch
            bucket = self._records.setdefault(epoch, {})
            bucket[stats.worker_index] = stats
            if epoch in self._published or len(bucket) < self.n_workers:
                return None
            # publish any earlier partial epochs first, then this one
            to_publish = self._collect_unpublished(before=epoch)
            self._published.add(epoch)
            summary = self._summarize(epoch, bucket)
            to_publish.append(summary)
            self.summaries.extend(to_publish)
        self._emit(to_publish)
        return summary

    def _summarize(self, epoch: int, bucket: dict[int, EpochStats]) -> EpochSummary:
        stats = list(bucket.values())
        n = len(stats)
        slowest = max(stats, key=lambda s: s.training_time_s)
        return EpochSummary(
            epoch=epoch,
            n_workers=n,
            mean_training_loss=sum(s.training_loss for s in stats) / n,
            mean_valid_loss=sum(s.valid_loss for s in stats) / n,
            mean_training_time_s=sum(s.training_time_s for s in stats) / n,
            mean_valid_time_s=sum(s.valid_time_s for s in stats) / n,
            slowest_worker=slowest.worker_index,
            slowest_time_s=slowest.training_time_s,
            ks=sum(s.ks for s in stats) / n,
            auc=sum(s.auc for s in stats) / n,
        )

    def _collect_unpublished(self, before: int | None = None) -> list[EpochSummary]:
        """Mark-published + summarize every reported-but-unpublished epoch
        (optionally only those ``< before``).  Caller holds the lock."""
        out: list[EpochSummary] = []
        for epoch in sorted(self._records):
            if before is not None and epoch >= before:
                break
            if epoch not in self._published and self._records[epoch]:
                self._published.add(epoch)
                out.append(self._summarize(epoch, self._records[epoch]))
        return out

    def _emit(self, summaries: list[EpochSummary]) -> None:
        for s in summaries:
            if self.board_path:
                fs.append_text(self.board_path, s.board_line())
            if self.on_epoch_complete:
                self.on_epoch_complete(s)

    def flush(self) -> list[EpochSummary]:
        """Publish every epoch that has at least one report but never
        reached quorum — called at job end so a worker that died without
        reporting doesn't leave its epochs permanently unpublished."""
        with self._lock:
            to_publish = self._collect_unpublished()
            self.summaries.extend(to_publish)
        self._emit(to_publish)
        return to_publish

    def pending_epochs(self) -> dict[int, int]:
        """epoch -> number of workers still missing (for stall diagnosis)."""
        with self._lock:
            return {
                e: self.n_workers - len(b)
                for e, b in self._records.items()
                if e not in self._published
            }
