"""Worker liveness monitoring.

Parity surface: the reference wires an ``AbstractLivelinessMonitor`` with a
1 s interval / 25 missed-beat budget (TensorflowApplicationMaster.java:87-112,
GlobalConfigurationKeys.java:75-79) — but no code path ever registers a task
with it and its kill action is commented out, so expiry is vestigial
(SURVEY.md §5.2).  This monitor is real: workers that miss the budget are
reported to the failure callback, which drives the coordinator's
checkpoint-restart policy.

Expiry is NOT terminal: a worker that was marked expired and then beats
again (a long XLA compile, a GC pause, a network partition healing)
recovers into ``alive()``, fires ``on_recovered``, and the flap is logged
and counted — without this, every transient pause permanently shrank the
fleet the coordinator believed in.  Note the recovery races the failure
policy by design: if ``on_expired`` already consumed restart budget or
triggered a relaunch, the recovery does not (cannot) undo it — the flap
log is the diagnostic trail for that case.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from shifu_tensorflow_tpu.obs import journal as obs_journal
from shifu_tensorflow_tpu.utils import logs

log = logs.get("liveness")


class LivenessMonitor:
    def __init__(
        self,
        interval_ms: int = 1000,
        max_missed: int = 25,
        on_expired: Callable[[str], None] | None = None,
        on_recovered: Callable[[str], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.interval_s = interval_ms / 1000.0
        self.max_missed = max_missed
        self.on_expired = on_expired
        self.on_recovered = on_recovered
        self._clock = clock
        self._last: dict[str, float] = {}
        self._expired: set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: expired→alive transitions observed (diagnostics)
        self.flaps = 0

    # ---- registration / beats ----
    def register(self, worker_id: str) -> None:
        with self._lock:
            self._last[worker_id] = self._clock()
            self._expired.discard(worker_id)

    def unregister(self, worker_id: str) -> None:
        with self._lock:
            self._last.pop(worker_id, None)
            self._expired.discard(worker_id)

    def beat(self, worker_id: str) -> None:
        recovered = False
        with self._lock:
            if worker_id in self._last:
                last = self._last[worker_id]
                self._last[worker_id] = self._clock()
                if worker_id in self._expired:
                    # the worker was written off but is beating again —
                    # recover it instead of ignoring it forever
                    self._expired.discard(worker_id)
                    self.flaps += 1
                    recovered = True
                    silence = self._clock() - last
        if recovered:
            # callback outside the lock (same discipline as check())
            log.warning(
                "worker %s recovered after %.1fs of silence (deadline "
                "%.1fs) — liveness flap #%d", worker_id, silence,
                self.deadline_s, self.flaps,
            )
            obs_journal.emit(
                "worker_recovered", plane="coordinator",
                worker_id=worker_id, silence_s=round(silence, 3),
                flap=self.flaps,
            )
            if self.on_recovered:
                self.on_recovered(worker_id)

    # ---- expiry ----
    @property
    def deadline_s(self) -> float:
        return self.interval_s * self.max_missed

    def check(self) -> list[str]:
        """Mark and return newly-expired workers."""
        now = self._clock()
        newly = []
        with self._lock:
            for wid, last in self._last.items():
                if wid not in self._expired and now - last > self.deadline_s:
                    self._expired.add(wid)
                    newly.append(wid)
        for wid in newly:
            obs_journal.emit("worker_expired", plane="coordinator",
                             worker_id=wid,
                             deadline_s=round(self.deadline_s, 3))
            if self.on_expired:
                self.on_expired(wid)
        return newly

    def expired(self) -> set[str]:
        with self._lock:
            return set(self._expired)

    def alive(self) -> set[str]:
        with self._lock:
            return set(self._last) - self._expired

    def ages(self) -> dict[str, float]:
        """Seconds since each registered worker's last beat — the
        diagnostics the coordinator bundles into timeout/health failures."""
        now = self._clock()
        with self._lock:
            return {wid: now - last for wid, last in self._last.items()}

    # ---- background loop ----
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
