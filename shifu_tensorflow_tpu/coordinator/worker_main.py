"""Subprocess worker entry: ``python -m shifu_tensorflow_tpu.coordinator.worker_main``.

The reference launched each worker as a real OS process in a YARN container
(AMRMCallbackHandler.java:159-182) with its configuration passed through
environment variables and localized files
(TensorflowTaskExecutor.java:200-238).  This is the equivalent launch shim:
the submitter writes the WorkerConfig as JSON (file or inline), spawns this
module, and consumes the process exit code — which makes kill-based fault
tolerance real (SIGKILL the process, watch checkpoint-restart recover),
something thread workers cannot model.

Run BEFORE any jax import side effects: when the environment pins
``JAX_PLATFORMS=cpu`` (tests; the driver's virtual-device harness) the
tunneled-TPU PJRT plugin is dropped before the first backend query, exactly
like the test conftest.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: list[str] | None = None) -> int:
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        from shifu_tensorflow_tpu.utils.jaxenv import force_cpu_backend

        force_cpu_backend()

    p = argparse.ArgumentParser(prog="worker_main")
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--config-file", help="path to a WorkerConfig JSON file")
    g.add_argument("--config-json", help="inline WorkerConfig JSON")
    g.add_argument("--config-stdin", action="store_true",
                   help="read WorkerConfig JSON from stdin (remote launch: "
                        "no shared filesystem required)")
    p.add_argument("--fail-at-epoch", type=int, default=None,
                   help="fault injection: abort at this epoch (tests)")
    p.add_argument("--run-tag", default=None,
                   help="opaque marker on the command line; the remote "
                        "launcher kills by matching it (pkill -f)")
    args = p.parse_args(argv)

    if args.config_file:
        with open(args.config_file) as f:
            payload = json.load(f)
    elif args.config_stdin:
        payload = json.loads(sys.stdin.read())
    else:
        payload = json.loads(args.config_json)

    from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig, run_worker

    cfg = WorkerConfig.from_json(payload)
    return run_worker(cfg, fail_at_epoch=args.fail_at_epoch)


if __name__ == "__main__":
    sys.exit(main())
