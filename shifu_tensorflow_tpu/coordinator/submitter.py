"""Job submitter — launches the coordinator and the worker fleet.

Parity surface: the reference's client submits the AM and polls every 10 s
until a terminal state (TensorflowClient.run/monitorApplication,
TensorflowClient.java:333,625-658); the AM requests containers and the NM
starts executors.  Here the submitter owns both halves directly: it starts
the Coordinator, launches N workers (in-process threads for tests and
single-host jobs; a ``spawn`` hook for real multi-host deployments), polls
status, and relaunches failed workers within the fault budget — the
checkpoint-restart replacement for backup containers.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from shifu_tensorflow_tpu.coordinator.coordinator import (
    Coordinator,
    JobSpec,
    JobState,
)
from shifu_tensorflow_tpu.coordinator.worker import WorkerConfig, run_worker
from shifu_tensorflow_tpu.data.splitter import split_training_data, total_line_count


@dataclass
class JobResult:
    state: JobState
    failure_reason: str | None
    epoch_summaries: list
    restarts_used: int
    wall_time_s: float


class JobSubmitter:
    def __init__(
        self,
        spec: JobSpec,
        make_worker_config: Callable[[str, tuple[str, int]], WorkerConfig],
        *,
        worker_runner: Callable[..., int] = run_worker,
        poll_interval_s: float = 0.2,
        drain_grace_s: float = 30.0,
        fault_injections: dict[str, int] | None = None,
    ):
        """``make_worker_config(worker_id, (host, port))`` builds each
        worker's config; ``fault_injections`` maps worker_id -> epoch to
        fail at (first launch only) for testing recovery."""
        self.spec = spec
        self.make_worker_config = make_worker_config
        self.worker_runner = worker_runner
        self.poll_interval_s = poll_interval_s
        self.drain_grace_s = drain_grace_s
        self.fault_injections = dict(fault_injections or {})
        self.coordinator = Coordinator(spec)
        self._threads: dict[str, threading.Thread] = {}
        self._launch_counts: dict[str, int] = {}

    def _launch(
        self, worker_id: str, addr: tuple[str, int], index: int | None = None
    ) -> None:
        cfg = self.make_worker_config(worker_id, addr)
        if cfg.worker_index is None:
            cfg.worker_index = index
        first_launch = self._launch_counts.get(worker_id, 0) == 0
        fail_at = self.fault_injections.get(worker_id) if first_launch else None
        self._launch_counts[worker_id] = self._launch_counts.get(worker_id, 0) + 1

        def target() -> None:
            self.worker_runner(cfg, fail_at_epoch=fail_at)

        t = threading.Thread(target=target, daemon=True, name=f"worker-{worker_id}")
        self._threads[worker_id] = t
        t.start()

    def run(self, timeout_s: float = 600.0) -> JobResult:
        t0 = time.monotonic()
        addr = self.coordinator.serve()
        worker_ids = [f"worker-{i}" for i in range(self.spec.n_workers)]
        for i, wid in enumerate(worker_ids):
            self._launch(wid, addr, index=i)

        relaunched: set[str] = set()
        try:
            while time.monotonic() - t0 < timeout_s:
                state = self.coordinator.state
                if state in (JobState.FINISHED, JobState.FAILED):
                    break
                # checkpoint-restart recovery: relaunch failed workers that
                # are within budget (coordinator keeps them restartable)
                for rec in self.coordinator.restartable_workers():
                    key = (rec.worker_id, rec.restarts)
                    if key not in relaunched:
                        relaunched.add(key)
                        self._launch(rec.worker_id, addr)
                time.sleep(self.poll_interval_s)
            else:
                self.coordinator._fail(f"job timeout after {timeout_s:.0f}s")
            # Drain: the chief finishing flips the job to FINISHED while
            # non-chief workers may still be mid-epoch; join them so their
            # in-flight epoch reports land before the result is snapshotted
            # (otherwise epoch_summaries races the last workers).  Skipped
            # for FAILED/timed-out jobs — those workers are known stuck and
            # the grace would just delay the error.
            if self.coordinator.state == JobState.FINISHED:
                drain_deadline = time.monotonic() + self.drain_grace_s
                for t in self._threads.values():
                    t.join(timeout=max(0.0, drain_deadline - time.monotonic()))
            try:
                self.coordinator.aggregator.flush()
            except Exception as e:
                # board-file IO must not turn a finished job into a raise;
                # the summaries list is already updated under the lock
                print(f"metrics flush failed: {e}", file=sys.stderr)
        finally:
            wall = time.monotonic() - t0
            result = JobResult(
                state=self.coordinator.state,
                failure_reason=self.coordinator.failure_reason,
                epoch_summaries=list(self.coordinator.aggregator.summaries),
                restarts_used=self.coordinator._failed_restarts,
                wall_time_s=wall,
            )
            self.coordinator.shutdown()
        return result


def make_job_spec(
    training_data_path: str,
    n_workers: int,
    *,
    epochs: int = 1,
    split_strategy: str = "size_aware",
    count_rows: bool = False,
    **spec_kwargs: Any,
) -> JobSpec:
    """Build a JobSpec from a data directory: split shards (parity with the
    AM's TrainingDataSet bootstrap, TensorflowSession.java:174-183) and
    optionally count rows (TOTAL_TRAINING_DATA_NUMBER parity)."""
    shards = split_training_data(training_data_path, n_workers, split_strategy)
    total = (
        total_line_count([p for s in shards for p in s.paths])
        if count_rows
        else 0
    )
    return JobSpec(
        n_workers=n_workers,
        shards=shards,
        total_rows=total,
        epochs=epochs,
        **spec_kwargs,
    )
